"""Parity of the device refinement primitives with the host numpy logic
they re-express (see pbccs_tpu/parallel/device_refine.py docstring)."""

import numpy as np
import pytest

from pbccs_tpu.models.arrow import mutations as mutlib
from pbccs_tpu.parallel import device_refine as dr


def _host_candidates(tpl):
    a = mutlib.enumerate_unique_arrays(tpl)
    return set(zip(a.start.tolist(), a.mtype.tolist(), a.new_base.tolist()))


def _dev_candidates(tpl, Jmax, allowed=None):
    import jax.numpy as jnp

    padded = np.full(Jmax, 4, np.int8)
    padded[: len(tpl)] = tpl
    s, e, t, b, v = dr.slot_candidates(
        jnp.asarray(padded), jnp.int32(len(tpl)),
        None if allowed is None else jnp.asarray(allowed))
    s, e, t, b, v = (np.asarray(x) for x in (s, e, t, b, v))
    return s, e, t, b, v


def test_slot_candidates_match_host_enumeration(rng):
    for _ in range(5):
        tpl = rng.integers(0, 4, int(rng.integers(5, 60))).astype(np.int8)
        s, e, t, b, v = _dev_candidates(tpl, 64)
        dev = set(zip(s[v].tolist(), t[v].tolist(), b[v].tolist()))
        assert dev == _host_candidates(tpl)
        # ends consistent with types
        host = mutlib.enumerate_unique_arrays(tpl)
        dev_ends = {(st, mt, nb): en for st, en, mt, nb in
                    zip(s[v], e[v], t[v], b[v])}
        for st, en, mt, nb in zip(host.start, host.end, host.mtype,
                                  host.new_base):
            assert dev_ends[(int(st), int(mt), int(nb))] == int(en)


def test_slot_candidates_nearby_filter(rng):
    tpl = rng.integers(0, 4, 50).astype(np.int8)
    centers = [mutlib.Mutation(10, 11, mutlib.SUBSTITUTION, 0),
               mutlib.Mutation(30, 30, mutlib.INSERTION, 2)]
    host = mutlib.unique_nearby_arrays(tpl, centers, 5)
    want = set(zip(host.start.tolist(), host.mtype.tolist(),
                   host.new_base.tolist()))

    import jax.numpy as jnp

    fav_start = jnp.asarray([10, 30], jnp.int32)
    fav_end = jnp.asarray([11, 30], jnp.int32)
    allowed = dr.nearby_allowed(fav_start, fav_end,
                                jnp.asarray([True, True]), 5, 64)
    allowed = np.asarray(allowed) & (np.arange(64) < len(tpl))
    s, e, t, b, v = _dev_candidates(tpl, 64, allowed=allowed)
    dev = set(zip(s[v].tolist(), t[v].tolist(), b[v].tolist()))
    assert dev == want


def test_greedy_matches_best_subset(rng):
    import jax.numpy as jnp

    for trial in range(8):
        L = 60
        tpl = rng.integers(0, 4, L).astype(np.int8)
        s, e, t, b, v = _dev_candidates(tpl, 64)
        scores = rng.normal(0, 3, len(s))
        scores[~v] = -np.inf
        fav = v & (scores > 0)

        host_muts = [mutlib.Mutation(int(s[i]), int(e[i]), int(t[i]),
                                     int(b[i]), float(scores[i]))
                     for i in np.nonzero(fav)[0]]
        want = mutlib.best_subset(host_muts, 10)
        want_keys = {(m.start, m.mtype, m.new_base) for m in want}

        taken = np.asarray(dr.greedy_well_separated(
            jnp.asarray(scores, jnp.float32), jnp.asarray(s),
            jnp.asarray(fav), 10, 64))
        got_keys = {(int(s[i]), int(t[i]), int(b[i]))
                    for i in np.nonzero(taken)[0]}
        assert got_keys == want_keys, trial


def test_splice_matches_apply_mutations(rng):
    import jax.numpy as jnp

    for trial in range(8):
        L = 50
        Jmax = 64
        tpl = rng.integers(0, 4, L).astype(np.int8)
        s, e, t, b, v = _dev_candidates(tpl, Jmax)
        scores = rng.normal(0, 3, len(s))
        scores[~v] = -np.inf
        fav = v & (scores > 0)
        taken = np.asarray(dr.greedy_well_separated(
            jnp.asarray(scores, jnp.float32), jnp.asarray(s),
            jnp.asarray(fav), 10, Jmax))
        muts = [mutlib.Mutation(int(s[i]), int(e[i]), int(t[i]), int(b[i]))
                for i in np.nonzero(taken)[0]]
        if not muts:
            continue
        want_tpl = mutlib.apply_mutations(tpl, muts)
        want_mtp = mutlib.target_to_query_positions(muts, L)

        padded = np.full(Jmax, 4, np.int8)
        padded[:L] = tpl
        new_tpl, new_tlen, mtp = dr.splice_templates(
            jnp.asarray(padded), jnp.int32(L), jnp.asarray(s),
            jnp.asarray(t), jnp.asarray(b), jnp.asarray(taken))
        new_tpl, new_tlen, mtp = (np.asarray(x) for x in
                                  (new_tpl, new_tlen, mtp))
        assert new_tlen == len(want_tpl)
        np.testing.assert_array_equal(new_tpl[:new_tlen], want_tpl)
        np.testing.assert_array_equal(mtp[: L + 1], want_mtp)


def test_rc_candidates_match_host(rng):
    import jax.numpy as jnp

    tpl = rng.integers(0, 4, 40).astype(np.int8)
    s, e, t, b, v = _dev_candidates(tpl, 64)
    host = mutlib.enumerate_unique_arrays(tpl)
    host_rc = mutlib.reverse_complement_arrays(host, len(tpl))
    want = {(int(st), int(mt), int(nb)): (int(rs), int(rb))
            for st, mt, nb, rs, rb in zip(host.start, host.mtype,
                                          host.new_base, host_rc.start,
                                          host_rc.new_base)}
    rs, rb = dr.rc_candidates(jnp.asarray(s), jnp.asarray(e),
                              jnp.asarray(b), jnp.int32(len(tpl)))
    rs, rb = np.asarray(rs), np.asarray(rb)
    for i in np.nonzero(v)[0]:
        assert want[(int(s[i]), int(t[i]), int(b[i]))] == \
            (int(rs[i]), int(rb[i]))


def test_greedy_separation_zero_keeps_all(rng):
    import jax.numpy as jnp

    scores = jnp.asarray([1.0, 2.0, 3.0])
    start = jnp.asarray([5, 5, 6], jnp.int32)
    fav = jnp.asarray([True, True, False])
    taken = np.asarray(dr.greedy_well_separated(scores, start, fav, 0, 16))
    np.testing.assert_array_equal(taken, [True, True, False])


def test_template_hash_distinguishes(rng):
    import jax.numpy as jnp

    tpl = rng.integers(0, 4, 40).astype(np.int8)
    pad = np.full(64, 4, np.int8)
    pad[:40] = tpl
    h0 = int(dr.template_hash(jnp.asarray(pad), jnp.int32(40)))
    # single-base change, length change, and pad-content change
    p2 = pad.copy()
    p2[17] = (p2[17] + 1) % 4
    assert int(dr.template_hash(jnp.asarray(p2), jnp.int32(40))) != h0
    assert int(dr.template_hash(jnp.asarray(pad), jnp.int32(39))) != h0
    p3 = pad.copy()
    p3[50] = 0  # beyond tlen: must not affect the hash
    assert int(dr.template_hash(jnp.asarray(p3), jnp.int32(40))) == h0
