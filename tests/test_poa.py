"""POA draft-stage tests, patterned on reference TestSparsePoa.cpp /
TestPoaConsensus.cpp: consensus recovery from noisy staggered reads,
orientation handling, per-read extents."""

import numpy as np
import pytest

from pbccs_tpu.models.arrow.params import decode_bases, encode_bases, revcomp
from pbccs_tpu.poa.sparse import SparsePoa
from pbccs_tpu.simulate import make_transition_track, random_snr, random_template, sample_read


def test_identical_reads_consensus():
    poa = SparsePoa()
    seq = encode_bases("ACGTACGTACGTTGCAACGT")
    for _ in range(3):
        assert poa.orient_and_add_read(seq) >= 0
    css, summaries = poa.find_consensus(min_coverage=1)
    assert decode_bases(css) == decode_bases(seq)
    for s in summaries:
        assert s.extent_on_read == (0, len(seq))
        assert s.extent_on_consensus == (0, len(seq))
        assert not s.reverse_complemented


def test_orientation_detection():
    poa = SparsePoa()
    seq = random_template(np.random.default_rng(1), 60)
    poa.orient_and_add_read(seq)
    key = poa.orient_and_add_read(revcomp(seq))
    assert key >= 0
    assert poa.reverse_complemented == [False, True]
    css, summaries = poa.find_consensus(min_coverage=1)
    assert decode_bases(css) == decode_bases(seq)
    assert summaries[1].extent_on_consensus == (0, 60)


def test_single_error_consensus():
    """Majority voting fixes one read's isolated substitution."""
    rng = np.random.default_rng(2)
    seq = random_template(rng, 50)
    bad = seq.copy()
    bad[25] = (bad[25] + 1) % 4
    poa = SparsePoa()
    for r in (seq, bad, seq):
        poa.orient_and_add_read(r)
    css, _ = poa.find_consensus(min_coverage=1)
    assert decode_bases(css) == decode_bases(seq)


@pytest.mark.parametrize("seed", range(3))
def test_noisy_reads_recover_template(seed):
    rng = np.random.default_rng(900 + seed)
    tpl = random_template(rng, 100)
    snr = random_snr(rng)
    trans = make_transition_track(tpl, snr)
    poa = SparsePoa()
    added = 0
    for k in range(8):
        read = sample_read(rng, tpl, trans)
        if k % 2:
            read = revcomp(read)
        if poa.orient_and_add_read(read) >= 0:
            added += 1
    assert added == 8
    min_cov = (added + 1) // 2 - 1
    css, summaries = poa.find_consensus(min_cov)
    # POA draft should be within a few edits of the truth
    import difflib
    ratio = difflib.SequenceMatcher(None, decode_bases(css), decode_bases(tpl)).ratio()
    assert ratio > 0.95, (ratio, decode_bases(css), decode_bases(tpl))


def test_staggered_local_reads():
    """Reads covering different windows still produce a joined consensus with
    correct extents (reference TestSparsePoa.cpp:62-126 pattern)."""
    rng = np.random.default_rng(3)
    tpl = random_template(rng, 120)
    poa = SparsePoa()
    windows = [(0, 80), (20, 100), (40, 120)]
    for s, e in windows:
        assert poa.orient_and_add_read(tpl[s:e]) >= 0
    css, summaries = poa.find_consensus(min_coverage=1)
    out = decode_bases(css)
    truth = decode_bases(tpl)
    assert out in truth or truth in out or len(out) >= 100
    # middle read maps fully onto the consensus
    rs, re_ = summaries[1].extent_on_read
    assert (rs, re_) == (0, 80)


def test_find_possible_variants():
    """Minority alleles left in the graph surface as scored variant
    candidates (reference PoaGraphTraversals.cpp:396-498 via
    TestPoaConsensus mutation-seeding patterns)."""
    from pbccs_tpu.models.arrow.mutations import (
        DELETION, INSERTION, SUBSTITUTION)

    base = encode_bases("ACGTACGTTGCAACGTACGT")
    sub = base.copy()
    sub[8] = (sub[8] + 2) % 4          # minority substitution
    dele = np.delete(base, 12)          # minority deletion
    ins = np.insert(base, 5, 3)         # minority insertion

    poa = SparsePoa()
    for r in (base, base, base, sub, dele, ins):
        assert poa.orient_and_add_read(r) >= 0
    css, _ = poa.find_consensus(min_coverage=2)
    assert decode_bases(css) == decode_bases(base)

    variants = poa.graph.find_possible_variants(poa.last_consensus_path)
    kinds = {(m.mtype, m.start) for m in variants}
    assert (SUBSTITUTION, 8) in kinds
    # deleted base sits in an "AA" homopolymer: either coordinate is the edit
    assert (DELETION, 11) in kinds or (DELETION, 12) in kinds
    assert (INSERTION, 5) in kinds


def test_find_possible_variants_requires_consensus():
    from pbccs_tpu.poa.graph import PoaGraph

    g = PoaGraph()
    g.add_first_read(encode_bases("ACGTAA"))
    with pytest.raises(RuntimeError):
        g.find_possible_variants([0, 1, 2, 3])
