"""CLI end-to-end: FASTA and BAM inputs -> CCS BAM + yield report.

Pattern: the reference's integration test drives the ccs executable over a
subread fixture (tests/python/test_tool_contract.py, TestData.h.in); here
the CLI entry runs in-process over simulated subreads.
"""

import numpy as np
import pytest

from pbccs_tpu.cli import run
from pbccs_tpu.io.bam import (
    BamHeader,
    BamReader,
    BamRecord,
    BamWriter,
    ReadGroupInfo,
    make_read_group_id,
)
from pbccs_tpu.io.fasta import write_fasta
from pbccs_tpu.models.arrow.params import decode_bases
from pbccs_tpu.simulate import simulate_zmw


def make_zmw_records(rng, movie, hole, tpl_len=60, n_passes=4):
    tpl, reads, strands, snr = simulate_zmw(rng, tpl_len, n_passes)
    recs = []
    for i, r in enumerate(reads):
        recs.append((f"{movie}/{hole}/{i * 100}_{i * 100 + len(r)}",
                     decode_bases(r)))
    return tpl, recs, snr


@pytest.mark.slow
def test_cli_fasta_end_to_end(rng, tmp_path):
    fasta = str(tmp_path / "subreads.fasta")
    records = []
    for hole in (1, 2):
        _, recs, _ = make_zmw_records(rng, "movie1", hole)
        records.extend(recs)
    write_fasta(fasta, records)

    out_bam = str(tmp_path / "out.bam")
    report = str(tmp_path / "report.csv")
    rc = run([out_bam, fasta, "--reportFile", report,
              "--skipChemistryCheck", "--numThreads", "2",
              "--logLevel", "WARN"])
    assert rc == 0

    with BamReader(out_bam) as br:
        results = list(br)
        assert {rg.read_type for rg in br.header.read_groups} == {"CCS"}
    assert len(results) == 2
    for rec in results:
        assert rec.name.endswith("/ccs")
        assert len(rec.seq) > 50
        assert len(rec.qual) == len(rec.seq)
        assert rec.tags["np"] >= 3
        assert rec.tags["rq"] > 900

    text = open(report).read()
    assert "Success -- CCS generated,2," in text


@pytest.mark.slow
def test_cli_bam_input_with_chemistry(rng, tmp_path):
    in_bam = str(tmp_path / "subreads.bam")
    movie = "m140905_042212_sidney_c100564852550000001823085912221377_s1_X0"
    header = BamHeader(read_groups=[
        ReadGroupInfo(movie, "SUBREAD", binding_kit="100356300",
                      sequencing_kit="100356200", basecaller_version="2.3.0")])
    rg_id = make_read_group_id(movie, "SUBREAD")
    _, recs, snr = make_zmw_records(rng, movie, 42, tpl_len=60, n_passes=4)
    with BamWriter(in_bam, header) as bw:
        for name, seq in recs:
            bw.write(BamRecord(name=name, seq=seq, tags={
                "RG": rg_id, "zm": 42, "cx": 3, "rq": 0.85,
                "sn": [float(s) for s in snr]}))

    out_bam = str(tmp_path / "out.bam")
    report = str(tmp_path / "report.csv")
    rc = run([out_bam, in_bam, "--reportFile", report,
              "--numThreads", "1", "--logLevel", "WARN"])
    assert rc == 0
    with BamReader(out_bam) as br:
        results = list(br)
    assert len(results) == 1
    assert results[0].name == f"{movie}/42/ccs"
    assert results[0].tags["zm"] == 42


def test_cli_whitelist_filters(rng, tmp_path):
    fasta = str(tmp_path / "subreads.fasta")
    records = []
    for hole in (1, 2, 3):
        _, recs, _ = make_zmw_records(rng, "movie1", hole)
        records.extend(recs)
    write_fasta(fasta, records)

    out_bam = str(tmp_path / "out.bam")
    rc = run([out_bam, fasta, "--zmws", "2",
              "--reportFile", str(tmp_path / "r.csv"),
              "--skipChemistryCheck", "--numThreads", "1",
              "--logLevel", "WARN"])
    assert rc == 0
    with BamReader(out_bam) as br:
        results = list(br)
    assert [r.tags["zm"] for r in results] == [2]


def test_cli_rejects_bad_whitelist(tmp_path):
    fasta = str(tmp_path / "x.fasta")
    write_fasta(fasta, [("m/1/0_4", "ACGT")])
    rc = run([str(tmp_path / "o.bam"), fasta, "--zmws", "all;1-3"])
    assert rc == 2


def test_cli_missing_input(tmp_path):
    rc = run([str(tmp_path / "o.bam"), str(tmp_path / "missing.bam"),
              "--skipChemistryCheck"])
    assert rc == 2


@pytest.mark.parametrize("bad", ["eight gigs", "0", "0.5"])
def test_cli_rejects_bad_mem_budget(tmp_path, bad):
    """Unparseable AND sub-byte budgets are usage errors before any
    input is read (HostBudget would otherwise reject '0' mid-run as an
    uncaught ValueError)."""
    fasta = str(tmp_path / "x.fasta")
    write_fasta(fasta, [("m/1/0_4", "ACGT")])
    rc = run([str(tmp_path / "o.bam"), fasta, "--memBudget", bad])
    assert rc == 2
