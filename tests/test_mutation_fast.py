"""Gather-free batched mutation scoring vs the per-mutation reference path.

The reference suite validates its fast (SSE) kernels against the scalar
implementations with randomized inputs (reference ConsensusCore
TestRecursors.cpp:291-440); here the pair is the per-mutation
extend_link_score / make_patch / mutated_window reference implementations
vs the batched one-hot-matmul fast paths that production routes through.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from pbccs_tpu.models.arrow import mutations as mutlib
from pbccs_tpu.models.arrow.scorer import ArrowMultiReadScorer, _make_patches
from pbccs_tpu.ops import mutation_score as ms
from pbccs_tpu.simulate import simulate_zmw


@pytest.fixture(scope="module")
def zmw_state():
    rng = np.random.default_rng(20260731)
    tpl, reads, strands, snr = simulate_zmw(rng, tpl_len=100, n_passes=5)
    sc = ArrowMultiReadScorer(tpl, snr, reads, strands,
                              [0] * len(reads), [len(tpl)] * len(reads))
    muts = mutlib.enumerate_unique(sc.tpl)
    rng.shuffle(muts)
    muts = muts[:64]
    L = len(sc.tpl)
    pos_f, end_f, mtype, base_f, pos_r, base_r = sc._mutation_arrays(muts)
    patches_f = _make_patches(sc.tpl_f.astype(jnp.int32), sc.trans_f,
                              sc.trans_table, jnp.int32(L),
                              jnp.asarray(pos_f), jnp.asarray(mtype),
                              jnp.asarray(base_f))
    patches_r = _make_patches(sc.tpl_r.astype(jnp.int32), sc.trans_r,
                              sc.trans_table, jnp.int32(L),
                              jnp.asarray(pos_r), jnp.asarray(mtype),
                              jnp.asarray(base_r))
    return sc, muts, (pos_f, end_f, mtype, base_f, pos_r, base_r), (patches_f, patches_r)


def test_make_patches_fast_matches_make_patch(zmw_state):
    sc, muts, (pos_f, _, mtype, base_f, _, _), _ = zmw_state
    L = len(sc.tpl)
    slow = jax.vmap(lambda p, t, b: ms.make_patch(
        sc.tpl_f.astype(jnp.int32), sc.trans_f, sc.trans_table, jnp.int32(L),
        p, t, b))(jnp.asarray(pos_f), jnp.asarray(mtype), jnp.asarray(base_f))
    fast = ms.make_patches_fast(
        sc.tpl_f.astype(jnp.int32), sc.trans_f, sc.trans_table, jnp.int32(L),
        jnp.asarray(pos_f), jnp.asarray(mtype), jnp.asarray(base_f))
    for a, b in zip(jax.tree.leaves(slow), jax.tree.leaves(fast)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


@pytest.mark.slow
def test_interior_fast_matches_extend_link(zmw_state):
    """Interior mutation LLs from the batched scorer equal the per-mutation
    extend+link reference, per read, on interior-mask positions."""
    sc, muts, (pos_f, end_f, mtype, _, _, _), (patches_f, patches_r) = zmw_state
    for r in range(sc.n_reads):
        ts, te, strand = int(sc._tstarts[r]), int(sc._tends[r]), int(sc._strands[r])
        p_w = np.where(strand == 0, pos_f - ts, te - end_f)
        e_w = np.where(strand == 0, end_f - ts, te - pos_f)
        interior = (p_w >= 3) & (e_w <= (te - ts) - 2)
        a = jax.tree.map(lambda x: x[r], sc.alpha)
        b = jax.tree.map(lambda x: x[r], sc.beta)
        read32 = jnp.asarray(sc._reads[r]).astype(jnp.int32)
        wt32 = sc.win_tpl[r].astype(jnp.int32)

        def slow_one(pf, ef, mt, patf, patr):
            p = jnp.where(strand == 0, pf - ts, te - ef)
            patch = jax.tree.map(
                lambda x, y: jnp.where(strand == 0, x, y), patf, patr)
            return ms.extend_link_score(
                read32, jnp.int32(sc._rlens[r]), wt32, sc.win_trans[r],
                sc.wlens[r], a, b, sc.a_prefix[r], sc.b_suffix[r],
                p, mt, patch)

        slow = np.asarray(jax.vmap(slow_one)(
            jnp.asarray(pos_f), jnp.asarray(end_f), jnp.asarray(mtype),
            patches_f, patches_r))
        fast = np.asarray(ms.interior_read_scores_fast(
            jnp.asarray(sc._reads[r]), jnp.int32(sc._rlens[r]),
            jnp.int32(strand), jnp.int32(ts), jnp.int32(te),
            sc.win_tpl[r], sc.win_trans[r], sc.wlens[r],
            a, b, sc.a_prefix[r], sc.b_suffix[r],
            jnp.asarray(pos_f), jnp.asarray(end_f), jnp.asarray(mtype),
            patches_f, patches_r))
        diff = np.abs(np.where(interior, slow - fast, 0.0))
        assert diff.max() < 2e-3, (r, diff.max())


@pytest.mark.slow
def test_edge_fast_matches_full_refill(zmw_state):
    """Boundary-mutation LLs from the short extension programs equal the
    full banded refill of the mutated window, per read (the reference's
    ExtendAlpha-to-end / ExtendBeta-to-begin vs full-refill equality fuzz,
    TestRecursors.cpp:291-440)."""
    sc, _, _, _ = zmw_state
    L = len(sc.tpl)
    # every mutation within 4 positions of either template end
    cand = [m for m in mutlib.enumerate_unique(sc.tpl)
            if m.start <= 4 or m.end >= L - 4]
    pos_f, end_f, mtype, base_f, pos_r, base_r = sc._mutation_arrays(cand)
    patches_f = _make_patches(sc.tpl_f.astype(jnp.int32), sc.trans_f,
                              sc.trans_table, jnp.int32(L),
                              jnp.asarray(pos_f), jnp.asarray(mtype),
                              jnp.asarray(base_f))
    patches_r = _make_patches(sc.tpl_r.astype(jnp.int32), sc.trans_r,
                              sc.trans_table, jnp.int32(L),
                              jnp.asarray(pos_r), jnp.asarray(mtype),
                              jnp.asarray(base_r))
    for r in range(sc.n_reads):
        ts, te, strand = int(sc._tstarts[r]), int(sc._tends[r]), int(sc._strands[r])
        wlen = te - ts
        p_w = np.where(strand == 0, pos_f - ts, te - end_f)
        e_w = np.where(strand == 0, end_f - ts, te - pos_f)
        is_ins = mtype == ms.INS
        overlap = np.where(is_ins, (ts <= end_f) & (pos_f <= te),
                           (ts < end_f) & (pos_f < te))
        edge = overlap & ~((p_w >= 3) & (e_w <= wlen - 2)) & (wlen >= 8)
        a = jax.tree.map(lambda x: x[r], sc.alpha)
        b = jax.tree.map(lambda x: x[r], sc.beta)

        fast = np.asarray(ms.edge_read_scores_fast(
            jnp.asarray(sc._reads[r]), jnp.int32(sc._rlens[r]),
            jnp.int32(strand), jnp.int32(ts), jnp.int32(te),
            sc.win_tpl[r], sc.win_trans[r], sc.wlens[r],
            a, b, sc.a_prefix[r], sc.b_suffix[r],
            jnp.asarray(pos_f), jnp.asarray(end_f), jnp.asarray(mtype),
            patches_f, patches_r))

        def refill_one(pf, ef, mt, patf, patr):
            p = jnp.where(strand == 0, pf - ts, te - ef)
            patch = jax.tree.map(
                lambda x, y: jnp.where(strand == 0, x, y), patf, patr)
            return ms.full_refill_score(
                jnp.asarray(sc._reads[r]).astype(jnp.int32),
                jnp.int32(sc._rlens[r]), sc.win_tpl[r].astype(jnp.int32),
                sc.win_trans[r], sc.wlens[r], p, mt, patch, sc._W)

        slow = np.asarray(jax.vmap(refill_one)(
            jnp.asarray(pos_f), jnp.asarray(end_f), jnp.asarray(mtype),
            patches_f, patches_r))
        diff = np.abs(np.where(edge, slow - fast, 0.0))
        assert edge.sum() > 0
        assert diff.max() < 2e-3, (r, diff.max(), int(np.argmax(diff)))


def test_mutated_windows_per_pair_matches_mutated_window(zmw_state):
    sc, muts, (pos_f, _, mtype, _, _, _), (patches_f, _) = zmw_state
    r = 0
    ts, te = int(sc._tstarts[r]), int(sc._tends[r])
    E = len(muts)
    wt_e = jnp.broadcast_to(sc.win_tpl[r].astype(jnp.int32),
                            (E,) + sc.win_tpl[r].shape)
    wtr_e = jnp.broadcast_to(sc.win_trans[r], (E,) + sc.win_trans[r].shape)
    wl_e = jnp.full(E, int(sc.wlens[r]), jnp.int32)
    p = jnp.asarray(pos_f) - ts
    fast = ms.mutated_windows_per_pair(wt_e, wtr_e, wl_e, p,
                                       jnp.asarray(mtype), patches_f)
    for i in range(0, E, 7):
        patch = jax.tree.map(lambda x: x[i], patches_f)
        slow = ms.mutated_window(sc.win_tpl[r].astype(jnp.int32),
                                 sc.win_trans[r], sc.wlens[r],
                                 p[i], jnp.asarray(mtype)[i], patch)
        np.testing.assert_array_equal(np.asarray(fast[0][i]), np.asarray(slow[0]))
        np.testing.assert_allclose(np.asarray(fast[1][i]), np.asarray(slow[1]),
                                   atol=1e-6)
        assert int(fast[2][i]) == int(slow[2])
