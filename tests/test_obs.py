"""Observability layer: metrics registry, trace spans, export surfaces.

Covers the tentpole contracts: histogram bucketing edge cases, concurrent
counter increments, measurement-scope isolation (the timing.reset()
replacement), span-tree nesting + Chrome-trace export round trip with
device-wait attribution, Prometheus text rendering, and a serve-session
test that scrapes the `metrics` verb and asserts stage counters advance.
"""

import json
import threading

import numpy as np
import pytest

from pbccs_tpu.obs import metrics as obs_metrics
from pbccs_tpu.obs import trace as obs_trace
from pbccs_tpu.obs.metrics import MetricsRegistry, log_buckets
from pbccs_tpu.runtime import timing


# ---------------------------------------------------------------- metrics


class TestCounters:
    def test_inc_and_negative_rejected(self):
        reg = MetricsRegistry()
        c = reg.counter("t_total")
        c.inc()
        c.inc(2.5)
        assert c.value == 3.5
        with pytest.raises(ValueError):
            c.inc(-1)

    def test_get_or_create_by_name_and_labels(self):
        reg = MetricsRegistry()
        a = reg.counter("t_total", stage="draft")
        b = reg.counter("t_total", stage="draft")
        c = reg.counter("t_total", stage="polish")
        assert a is b and a is not c

    def test_kind_collision_raises(self):
        reg = MetricsRegistry()
        reg.counter("x")
        with pytest.raises(TypeError):
            reg.gauge("x")

    def test_concurrent_increments_exact(self):
        """8 threads x 5000 increments must lose nothing."""
        reg = MetricsRegistry()
        c = reg.counter("t_total")
        g = reg.gauge("t_gauge")
        n, per = 8, 5000

        def worker():
            for _ in range(per):
                c.inc()
                g.inc()

        threads = [threading.Thread(target=worker) for _ in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value == n * per
        assert g.value == n * per


class TestHistogram:
    def test_log_buckets(self):
        b = log_buckets(1.0, 100.0, 10.0)
        assert b == (1.0, 10.0, 100.0)
        with pytest.raises(ValueError):
            log_buckets(0.0, 1.0)
        with pytest.raises(ValueError):
            log_buckets(1.0, 2.0, 1.0)

    def test_bucketing_edges(self):
        """A value exactly on a bound lands in that bound's bucket
        (Prometheus le semantics); below-first and above-last land in the
        first and +Inf buckets."""
        reg = MetricsRegistry()
        h = reg.histogram("h", buckets=(1.0, 10.0, 100.0))
        for v in (0.5, 1.0, 1.0000001, 10.0, 99.0, 100.0, 1e9):
            h.observe(v)
        counts, s, n = h.snapshot()
        # bucket semantics: <=1, <=10, <=100, +Inf
        assert counts == (2, 2, 2, 1)
        assert n == 7
        assert s == pytest.approx(0.5 + 1 + 1.0000001 + 10 + 99 + 100 + 1e9)

    def test_prometheus_cumulative_rendering(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat_seconds", "latency", buckets=(1.0, 10.0))
        for v in (0.5, 5.0, 50.0):
            h.observe(v)
        text = reg.render_prometheus()
        assert '# TYPE lat_seconds histogram' in text
        assert 'lat_seconds_bucket{le="1"} 1' in text
        assert 'lat_seconds_bucket{le="10"} 2' in text
        assert 'lat_seconds_bucket{le="+Inf"} 3' in text
        assert 'lat_seconds_count 3' in text

    def test_concurrent_observes_exact(self):
        reg = MetricsRegistry()
        h = reg.histogram("h", buckets=(0.5,))
        threads = [threading.Thread(
            target=lambda: [h.observe(1.0) for _ in range(2000)])
            for _ in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        counts, s, n = h.snapshot()
        assert n == 12000 and counts == (0, 12000) and s == 12000.0


class TestScopes:
    def test_scope_reports_deltas_only(self):
        reg = MetricsRegistry()
        c = reg.counter("t_total")
        c.inc(10)
        scope = reg.scope()
        c.inc(5)
        assert scope.counter_value("t_total") == 5.0

    def test_concurrent_scopes_do_not_clobber(self):
        """The satellite contract: two measurement windows over one
        registry are independent -- no global reset."""
        reg = MetricsRegistry()
        c = reg.counter("t_total")
        bench = reg.scope()
        c.inc(3)
        engine = reg.scope()     # opened later: sees only what follows
        c.inc(4)
        assert bench.counter_value("t_total") == 7.0
        assert engine.counter_value("t_total") == 4.0
        # opening yet another scope (the old reset()) changes neither
        reg.scope()
        assert bench.counter_value("t_total") == 7.0
        assert engine.counter_value("t_total") == 4.0

    def test_histogram_delta(self):
        reg = MetricsRegistry()
        h = reg.histogram("h", buckets=(1.0,))
        h.observe(0.5)
        scope = reg.scope()
        h.observe(0.5)
        h.observe(2.0)
        counts, s, n = scope.delta()[("h", ())]
        assert counts == (1, 1) and n == 2 and s == 2.5

    def test_timing_shim_windows(self):
        """timing.reset() only moves the module window; an explicit
        window is unaffected (bench vs live engine isolation)."""
        win = timing.window()
        timing.add_stage("test_obs_stage", 1.0)
        timing.reset()          # module window restarts ...
        assert timing.stage_seconds().get("test_obs_stage") is None
        # ... but the explicit window still sees the pre-reset second
        assert timing.stage_seconds(win)["test_obs_stage"] == \
            pytest.approx(1.0)


# ------------------------------------------------------------------ trace


class TestTrace:
    def test_span_tree_nesting_and_round_trip(self):
        tracer = obs_trace.Tracer()
        with tracer.span("polish", zmws=2):
            with tracer.span("polish.round", round=0):
                pass
            with tracer.span("polish.round", round=1):
                pass
        chrome = json.loads(json.dumps(tracer.to_chrome()))  # wire trip
        events = chrome["traceEvents"]
        assert [e["name"] for e in events] == \
            ["polish", "polish.round", "polish.round"]
        tree = obs_trace.span_tree(chrome)
        roots = tree[None]
        assert len(roots) == 1 and roots[0]["name"] == "polish"
        children = tree[roots[0]["id"]]
        assert [c["args"]["round"] for c in children] == [0, 1]
        # children are contained in the parent's [ts, ts+dur]
        for c in children:
            assert c["ts"] >= roots[0]["ts"]
            assert c["ts"] + c["dur"] <= \
                roots[0]["ts"] + roots[0]["dur"] + 1e-6

    def test_device_wait_attribution(self):
        """timing.device_fetch inside a span attributes its blocking time
        to the innermost open span."""
        tracer = obs_trace.Tracer()
        prev = obs_trace.set_tracer(tracer)
        try:
            with obs_trace.span("polish"):
                with obs_trace.span("polish.round", round=0):
                    timing.device_fetch(np.arange(4))
        finally:
            obs_trace.set_tracer(prev)
        spans = {s.name: s for s in tracer.finished_spans()}
        assert spans["polish.round"].device_wait_s >= 0.0
        ev = [e for e in tracer.to_chrome()["traceEvents"]
              if e["name"] == "polish.round"][0]
        assert "device_wait_ms" in ev["args"]

    def test_disabled_tracer_is_noop(self):
        prev = obs_trace.set_tracer(None)
        try:
            with obs_trace.span("x") as sp:
                assert sp is None
            obs_trace.add_device_wait(1.0)  # must not raise
        finally:
            obs_trace.set_tracer(prev)

    def test_span_cap_bounds_capture(self):
        """A capture left running must not grow unboundedly: past
        max_spans new spans are dropped and counted."""
        tracer = obs_trace.Tracer(max_spans=3)
        for i in range(5):
            with tracer.span("s", i=i) as sp:
                assert (sp is not None) == (i < 3)
        assert len(tracer.finished_spans()) == 3
        chrome = tracer.to_chrome()
        assert chrome["droppedSpans"] == 2

    def test_install_and_clear_are_cas(self):
        """install_tracer refuses to hijack a live capture; clear_tracer
        only uninstalls its own."""
        prev = obs_trace.set_tracer(None)
        try:
            a, b = obs_trace.Tracer(), obs_trace.Tracer()
            assert obs_trace.install_tracer(a)
            assert not obs_trace.install_tracer(b)   # a's capture survives
            assert not obs_trace.clear_tracer(b)     # b can't clear a's
            assert obs_trace.get_tracer() is a
            assert obs_trace.clear_tracer(a)
            assert obs_trace.get_tracer() is None
        finally:
            obs_trace.set_tracer(prev)

    def test_spans_across_threads_keep_separate_stacks(self):
        tracer = obs_trace.Tracer()

        def worker(i):
            with tracer.span("w", i=i):
                pass

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        spans = tracer.finished_spans()
        assert len(spans) == 4
        assert all(s.parent is None for s in spans)  # no cross-thread nest


# ------------------------------------------------------- serve integration


class TestServeMetrics:
    def test_metrics_verb_scrape_advances(self):
        """A serve session scrapes the `metrics` verb before and after a
        submit: admission and stage counters must advance, and the body
        must be valid Prometheus text."""
        from pbccs_tpu.serve.client import CcsClient
        from pbccs_tpu.serve.server import CcsServer
        from tests.test_serve import stub_engine

        def scrape(body: str) -> dict[str, float]:
            out = {}
            for line in body.splitlines():
                if line and not line.startswith("#"):
                    name, _, v = line.rpartition(" ")
                    out[name] = float(v)
            return out

        eng = stub_engine(max_batch=2, max_wait_ms=50.0).start()
        srv = CcsServer(eng, port=0).start()
        try:
            with CcsClient(srv.host, srv.port) as cli:
                before = scrape(cli.metrics())
                assert "ccs_serve_admitted_total" in before
                for i in range(3):
                    msg = cli.submit(f"m/{i}", ["ACGTACGT"] * 4) \
                        .reply(timeout=10.0)
                    assert msg["status"] == "Success"
                after = scrape(cli.metrics())
                assert after["ccs_serve_admitted_total"] >= \
                    before["ccs_serve_admitted_total"] + 3
                assert after["ccs_serve_completed_total"] >= \
                    before["ccs_serve_completed_total"] + 3
                stage_key = 'ccs_stage_seconds_total{stage="serve.prep"}'
                assert after[stage_key] > before.get(stage_key, 0.0)
                lat = 'ccs_serve_request_latency_seconds_count'
                assert after[lat] >= before.get(lat, 0.0) + 3
                # flush accounting: the three submits flushed at least one
                # fill batch (max_batch=2) and one deadline batch
                flushes = [k for k in after if
                           k.startswith("ccs_serve_flushes_total")]
                assert sum(after[k] for k in flushes) >= \
                    sum(before.get(k, 0.0) for k in flushes) + 2
                # status carries the /metrics-style snapshot
                st = cli.status()
                assert "ccs_serve_admitted_total" in st["metrics"]
        finally:
            srv.shutdown()
            eng.close()

    def test_trace_verb_capture_round_trip(self):
        from pbccs_tpu.serve.client import CcsClient
        from pbccs_tpu.serve.server import CcsServer
        from tests.test_serve import stub_engine

        eng = stub_engine(max_batch=1, max_wait_ms=50.0).start()
        srv = CcsServer(eng, port=0).start()
        try:
            with CcsClient(srv.host, srv.port) as cli:
                assert cli.trace("stop")["state"] == "not_running"
                assert cli.trace("start")["state"] == "started"
                assert cli.trace("start")["state"] == "already_running"
                msg = cli.submit("m/1", ["ACGTACGT"] * 4).reply(timeout=10.0)
                assert msg["status"] == "Success"
                reply = cli.trace("stop")
                assert reply["state"] == "stopped"
                names = {e["name"]
                         for e in reply["trace"]["traceEvents"]}
                assert "serve.prep" in names and "serve.polish" in names
        finally:
            srv.shutdown()
            eng.close()
            assert obs_trace.get_tracer() is None  # capture never leaks

    def test_trace_bad_action_is_structured_error(self):
        from pbccs_tpu.serve.client import CcsClient, ServeError
        from pbccs_tpu.serve.server import CcsServer
        from tests.test_serve import stub_engine

        eng = stub_engine().start()
        srv = CcsServer(eng, port=0).start()
        try:
            with CcsClient(srv.host, srv.port) as cli:
                with pytest.raises(ServeError) as ei:
                    cli.trace("frobnicate")
                assert ei.value.code == "bad_request"
        finally:
            srv.shutdown()
            eng.close()


# ------------------------------------------------------------- summary/CLI


class TestSummaryAndRegistry:
    def test_summary_table_from_scope(self):
        reg = MetricsRegistry()
        scope = reg.scope()
        reg.counter("ccs_demo_total", stage="x").inc(2)
        reg.histogram("ccs_demo_seconds", buckets=(1.0,)).observe(0.5)
        table = reg.summary_table(scope)
        assert "ccs_demo_total{stage=x}" in table
        assert "n=1" in table

    def test_default_registry_is_shared(self):
        assert obs_metrics.default_registry() is \
            obs_metrics.default_registry()

    def test_prometheus_escaping(self):
        reg = MetricsRegistry()
        reg.counter("c_total", stage='we"ird\n').inc()
        text = reg.render_prometheus()
        assert 'stage="we\\"ird\\n"' in text


# ------------------------------------------- fleet observability plane


class TestTraceContext:
    def test_ctx_span_exports_trace_identity(self):
        tracer = obs_trace.Tracer(tag="t1")
        with tracer.span("serve.prep",
                         ctx={"trace_id": "abc", "span_id": "rt-q1"}):
            with tracer.span("inner"):
                pass
        events = {e["name"]: e for e in
                  tracer.to_chrome()["traceEvents"]}
        prep = events["serve.prep"]["args"]
        assert prep["trace_id"] == "abc"
        assert prep["remote_parent"] == "rt-q1"
        assert prep["span_id"] == "t1-0"
        # children INHERIT the trace id through the thread stack
        inner = events["inner"]["args"]
        assert inner["trace_id"] == "abc"
        assert "remote_parent" not in inner

    def test_add_span_pins_explicit_span_id(self):
        tracer = obs_trace.Tracer()
        sp = tracer.add_span("router.request", 0.25,
                             ctx={"trace_id": "abc", "span_id": "cl-0"},
                             span_id="rt-q9", replica="r1")
        assert sp is not None and not sp.open
        ev = tracer.to_chrome()["traceEvents"][0]
        assert ev["args"]["span_id"] == "rt-q9"
        assert ev["args"]["remote_parent"] == "cl-0"
        assert ev["dur"] == pytest.approx(250_000, rel=0.05)

    def test_current_context_round_trip(self):
        tracer = obs_trace.Tracer(tag="cli")
        prev = obs_trace.set_tracer(None)
        try:
            assert obs_trace.install_tracer(tracer)
            assert obs_trace.current_context() is None  # not in a span
            with obs_trace.span("load", ctx={"trace_id": "t",
                                             "span_id": None}):
                ctx = obs_trace.current_context()
                assert ctx == {"trace_id": "t", "span_id": "cli-0"}
            assert obs_trace.clear_tracer(tracer)
        finally:
            obs_trace.set_tracer(prev)

    def test_open_spans_tagged_not_zero_duration(self):
        """Satellite contract: a mid-flight capture tags still-open
        spans open=true with duration measured to the capture instant,
        and the export metadata surfaces dropped/open counts."""
        import time as _time

        tracer = obs_trace.Tracer(max_spans=2)
        with tracer.span("outer"):
            _time.sleep(0.01)
            chrome = tracer.to_chrome()       # captured mid-flight
        with tracer.span("later"):
            pass
        with tracer.span("past-cap"):
            pass
        ev = chrome["traceEvents"][0]
        assert ev["args"]["open"] is True
        assert ev["dur"] >= 10_000            # >= the 10 ms slept, in us
        assert chrome["meta"]["open_spans"] == 1
        final = tracer.to_chrome()
        assert "open" not in final["traceEvents"][0]["args"]
        assert final["meta"]["dropped_spans"] == 1
        assert final["meta"]["open_spans"] == 0
        assert "origin_unix" in final["meta"]


class TestSeriesCap:
    def test_cap_drops_new_label_sets_and_counts(self):
        reg = MetricsRegistry(max_series_per_name=2)
        a = reg.counter("ccs_x_total", peer="a")
        b = reg.counter("ccs_x_total", peer="b")
        c = reg.counter("ccs_x_total", peer="c")   # past the cap
        d = reg.counter("ccs_x_total", peer="d")
        for m in (a, b, c, d):
            m.inc()
        text = reg.render_prometheus()
        assert 'ccs_x_total{peer="a"}' in text
        assert 'ccs_x_total{peer="b"}' in text
        assert 'peer="c"' not in text and 'peer="d"' not in text
        assert ('ccs_metrics_series_dropped_total{metric="ccs_x_total"}'
                ' 2') in text
        # existing series keep working past the cap
        assert reg.counter("ccs_x_total", peer="a") is a
        # a dropped label set counts ONCE and hands back the SAME
        # cached detached instrument on every later lookup (no
        # per-update allocation, no runaway drop counter)
        again = reg.counter("ccs_x_total", peer="c")
        assert again is c
        again.inc()
        text2 = reg.render_prometheus()
        assert ('ccs_metrics_series_dropped_total{metric="ccs_x_total"}'
                ' 2') in text2
        with pytest.raises(TypeError):
            reg.gauge("ccs_x_total", peer="c")   # kind mismatch holds

    def test_dropped_instrument_is_usable_but_detached(self):
        reg = MetricsRegistry(max_series_per_name=1)
        reg.histogram("h_seconds", buckets=(1.0,), peer="a")
        ghost = reg.histogram("h_seconds", buckets=(1.0,), peer="b")
        ghost.observe(0.5)   # must not raise
        assert ghost.count == 1
        assert ('h_seconds', (("peer", "b"),)) not in reg.snapshot()

    def test_set_series_cap_validates(self):
        reg = MetricsRegistry()
        with pytest.raises(ValueError):
            reg.set_series_cap(0)
        reg.set_series_cap(3)


class TestFederationHelpers:
    def test_relabel_injects_into_all_sample_forms(self):
        from pbccs_tpu.obs.metrics import relabel_exposition

        body = ('# TYPE a_total counter\n'
                'a_total 3\n'
                'a_total{x="1"} 4\n'
                'h_bucket{le="+Inf"} 7\n')
        out = relabel_exposition(body, replica="r:1")
        assert 'a_total{replica="r:1"} 3' in out
        assert 'a_total{x="1",replica="r:1"} 4' in out
        assert 'h_bucket{le="+Inf",replica="r:1"} 7' in out
        assert '# TYPE a_total counter' in out

    def test_merge_groups_by_name_with_one_type_line(self):
        from pbccs_tpu.obs.metrics import merge_expositions

        merged = merge_expositions([
            "# TYPE a_total counter\na_total 1\n",
            '# TYPE a_total counter\na_total{replica="x"} 2\n',
        ])
        assert merged.count("# TYPE a_total counter") == 1
        assert "a_total 1" in merged
        assert 'a_total{replica="x"} 2' in merged

    def test_histogram_quantile(self):
        from pbccs_tpu.obs.metrics import histogram_quantile

        bounds = (0.1, 0.2, 0.4)
        assert histogram_quantile((10, 0, 0, 0), bounds, 0.99) == 0.1
        assert histogram_quantile((50, 49, 1, 0), bounds, 0.99) == 0.2
        assert histogram_quantile((0, 0, 0, 5), bounds, 0.5) == 0.4
        import math
        assert math.isnan(histogram_quantile((0, 0, 0, 0), bounds, 0.5))

    def test_histogram_quantile_edge_shapes(self):
        """Empty/all-zero counts, a single bucket, and degenerate
        no-finite-bounds layouts answer (NaN or a bound), never raise."""
        import math

        from pbccs_tpu.obs.metrics import histogram_quantile

        # empty layouts: no counts at all / no finite bounds
        assert math.isnan(histogram_quantile((), (), 0.5))
        assert math.isnan(histogram_quantile((5,), (), 0.9))
        # all-zero counts at every width
        assert math.isnan(histogram_quantile((0,), (), 0.5))
        assert math.isnan(histogram_quantile((0, 0), (1.0,), 0.5))
        # a single bucket: everything lands on its one bound
        assert histogram_quantile((3, 0), (1.0,), 0.01) == 1.0
        assert histogram_quantile((3, 0), (1.0,), 0.99) == 1.0
        # overflow-only observations report the last finite bound
        assert histogram_quantile((0, 7), (1.0,), 0.5) == 1.0
        # q=0 and q=1 extremes stay in range
        assert histogram_quantile((1, 1, 0), (0.1, 0.2), 0.0) == 0.1
        assert histogram_quantile((1, 1, 0), (0.1, 0.2), 1.0) == 0.2

    def test_hostile_label_values_roundtrip_federation(self):
        """Label values containing backslash, quote, newline, and a
        literal `}` must survive render -> relabel -> merge -> parse
        without corrupting the exposition (the values the fleet mints
        from network identity are not this hostile; a chaos test's
        are)."""
        from pbccs_tpu.obs.metrics import (MetricsRegistry,
                                           merge_expositions,
                                           parse_exposition,
                                           relabel_exposition)

        hostile = 'a\\b"c}d\ne'
        reg = MetricsRegistry()
        reg.counter("ccs_hostile_total", "t", path=hostile).inc(3)
        body = reg.render_prometheus()
        relabeled = relabel_exposition(body, replica="r:1")
        merged = merge_expositions([relabeled])
        parsed = parse_exposition(merged)
        key = ("ccs_hostile_total",
               (("path", hostile), ("replica", "r:1")))
        assert parsed[key] == 3.0
        # the relabel actually landed (a corrupted line would have been
        # passed through unlabeled)
        assert all("replica" in dict(labels)
                   for (_n, labels) in parsed)

    def test_relabel_escapes_injected_label_value(self):
        from pbccs_tpu.obs.metrics import (parse_exposition,
                                           relabel_exposition)

        out = relabel_exposition("a_total 1\n", replica='x"y\\z')
        assert parse_exposition(out)[
            ("a_total", (("replica", 'x"y\\z'),))] == 1.0

    def test_merge_empty_and_comment_only_parts(self):
        from pbccs_tpu.obs.metrics import merge_expositions

        assert merge_expositions([]) == ""
        assert merge_expositions(["", "# HELP x_total h\n"]) == ""
        merged = merge_expositions(["", "# TYPE a_total counter\n"
                                        "a_total 1\n"])
        assert "a_total 1" in merged


class TestHttpExposition:
    """obs/httpexp.py error paths: 404 on unknown paths, a scrape
    racing server shutdown degrades to a connection error (never a
    handler traceback), and /healthz tracks the health callback
    through an engine drain."""

    @staticmethod
    def _stop(server):
        # shutdown() only stops serve_forever; server_close() releases
        # the listening socket so later connects fail fast and tests
        # don't leak fds for the process lifetime
        server.shutdown()
        server.server_close()

    @staticmethod
    def _get(port, path, timeout=5.0):
        import http.client

        conn = http.client.HTTPConnection("127.0.0.1", port,
                                          timeout=timeout)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            return resp.status, resp.read()
        finally:
            conn.close()

    def test_unknown_path_is_404(self):
        from pbccs_tpu.obs.httpexp import start_metrics_http

        server = start_metrics_http(lambda: "x 1\n")
        try:
            status, body = self._get(server.server_port, "/nope")
            assert status == 404 and b"not found" in body
            status, _ = self._get(server.server_port,
                                  "/metrics/../../etc/passwd")
            assert status == 404
        finally:
            self._stop(server)

    def test_render_error_is_500_and_server_survives(self):
        from pbccs_tpu.obs.httpexp import start_metrics_http

        calls = [0]

        def render():
            calls[0] += 1
            if calls[0] == 1:
                raise RuntimeError("boom")
            return "ok_total 1\n"

        server = start_metrics_http(render)
        try:
            status, body = self._get(server.server_port, "/metrics")
            assert status == 500 and b"boom" in body
            status, body = self._get(server.server_port, "/metrics")
            assert status == 200 and b"ok_total" in body
        finally:
            self._stop(server)

    def test_healthz_tracks_health_callback(self):
        from pbccs_tpu.obs.httpexp import start_metrics_http

        healthy = [True]
        server = start_metrics_http(lambda: "x 1\n",
                                    health=lambda: healthy[0])
        try:
            status, body = self._get(server.server_port, "/healthz")
            assert status == 200 and body == b"ok\n"
            healthy[0] = False
            status, body = self._get(server.server_port, "/healthz")
            assert status == 503 and body == b"draining\n"
            # a RAISING health callback reads as unhealthy, not a 500
            server2 = start_metrics_http(
                lambda: "x 1\n",
                health=lambda: (_ for _ in ()).throw(RuntimeError()))
            try:
                status, _ = self._get(server2.server_port, "/healthz")
                assert status == 503
            finally:
                self._stop(server2)
        finally:
            self._stop(server)

    def test_healthz_accurate_during_engine_drain(self):
        import numpy as np

        from pbccs_tpu.obs.httpexp import start_metrics_http
        from pbccs_tpu.pipeline import Failure, PreparedZmw
        from pbccs_tpu.serve.engine import CcsEngine, ServeConfig

        eng = CcsEngine(
            config=ServeConfig(max_batch=1, max_wait_ms=20.0),
            prep_fn=lambda c, s: (None, PreparedZmw(
                c, np.zeros(8, np.int8), [], 1, 0, 0.0)),
            polish_fn=lambda p, s: [(Failure.SUCCESS, None)
                                    for _ in p]).start()
        server = start_metrics_http(eng.metrics_text,
                                    health=eng.accepting)
        try:
            assert self._get(server.server_port, "/healthz")[0] == 200
            eng.close()   # drain begins: accepting flips false
            assert self._get(server.server_port, "/healthz")[0] == 503
        finally:
            self._stop(server)

    def test_scrape_racing_shutdown_degrades(self):
        """Scrapes fired while the server shuts down either answer or
        fail THEIR socket; none leaves the server wedged and the port
        is dead afterwards."""
        import threading

        from pbccs_tpu.obs.httpexp import start_metrics_http

        server = start_metrics_http(lambda: "x 1\n" * 200)
        port = server.server_port
        outcomes = []

        def scrape():
            try:
                outcomes.append(self._get(port, "/metrics",
                                          timeout=2.0)[0])
            except Exception:  # noqa: BLE001 -- any transport-level
                # failure (reset, torn reply, timeout) is the expected
                # degradation; a traceback OUT of the server is not
                outcomes.append("conn_error")

        threads = [threading.Thread(target=scrape) for _ in range(8)]
        for i, t in enumerate(threads):
            t.start()
            if i == 3:
                self._stop(server)
        for t in threads:
            t.join(timeout=5.0)
        assert len(outcomes) == 8
        assert all(o in (200, "conn_error") for o in outcomes), outcomes
        import pytest as _pytest
        with _pytest.raises(OSError):
            self._get(port, "/metrics", timeout=1.0)


class TestFlightRecorder:
    def test_ring_bounds_and_gauges(self):
        from pbccs_tpu.obs import flight

        rec = flight.FlightRecorder(capacity=4)
        for i in range(10):
            rec.record_round("b0", i, live=8 - i if i < 8 else 0,
                             n_zmws=8, z=16)
        snap = rec.snapshot()
        assert len(snap) == 4                  # ring stays bounded
        assert snap[-1]["round"] == 9
        assert snap[-1]["padding_waste"] == 0.5
        reg = obs_metrics.default_registry()
        snapshot = reg.snapshot()
        key = ("ccs_refine_padding_waste", ())
        assert key in snapshot

    def test_dump_logs_and_keeps(self):
        from pbccs_tpu.obs import flight

        rec = flight.FlightRecorder(capacity=8)
        rec.record_round("b1", 0, 4, 4, 8)

        class FakeLog:
            def __init__(self):
                self.lines = []

            def warn(self, msg):
                self.lines.append(msg)

        log = FakeLog()
        out = rec.dump("test-reason", log)
        assert len(out) == 1
        assert log.lines and "test-reason" in log.lines[0]
        assert rec.snapshot()                  # keep=True by default


class TestStageHistogramsAndSlo:
    def test_stage_latency_and_slo_counters_advance(self):
        """A served request leaves per-stage samples and, with a tiny
        --sloP99Ms, a burn-rate violation; the status verb carries the
        slo block."""
        from pbccs_tpu.serve.client import CcsClient
        from pbccs_tpu.serve.server import CcsServer
        from tests.test_serve import stub_engine

        reg = obs_metrics.default_registry()
        scope = reg.scope()
        eng = stub_engine(max_batch=1, max_wait_ms=20.0)
        # impossible objective: every request violates
        object.__setattr__(eng.config, "slo_p99_ms", 1e-6)
        eng.start()
        srv = CcsServer(eng, port=0).start()
        try:
            with CcsClient(srv.host, srv.port) as cli:
                msg = cli.submit("m/1", ["ACGTACGT"] * 4).reply(10.0)
                assert msg["status"] == "Success"
                st = cli.status()
                assert st["slo"]["enabled"] is True
                assert st["slo"]["target_p99_ms"] == 1e-6
        finally:
            srv.shutdown()
            eng.close()
        delta = scope.delta()
        stages = {k[1][0][1] for k, v in delta.items()
                  if k[0] == "ccs_serve_stage_latency_seconds"
                  and v[2] > 0}
        assert {"admission", "prepare", "queue", "dispatch", "polish",
                "emit"} <= stages
        assert scope.counter_value("ccs_slo_requests_total") >= 1
        assert scope.counter_value("ccs_slo_violations_total") >= 1
