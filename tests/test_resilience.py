"""Resilience subsystem tests: fault injection, retry, quarantine
bisection, watchdog, checkpoint journal, and the serve-side retry/
watchdog integrations.

The unit layers (faults/retry/watchdog/checkpoint/bisection control
flow) run with stubs and no device work; two pipeline-level tests pin
the batch-fallback parity contract -- a poisoned batch must yield
byte-identical results for every surviving ZMW, on both the bisection
path and the legacy serial path -- against the real polish core.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from pbccs_tpu.obs.metrics import default_registry
from pbccs_tpu.pipeline import (
    Chunk,
    ConsensusResult,
    ConsensusSettings,
    Failure,
    MappedRead,
    PreparedZmw,
    Subread,
)
from pbccs_tpu.resilience import (checkpoint, faults, quarantine, resources,
                                  retry, watchdog)
from pbccs_tpu.resilience.faults import FaultSpecError, InjectedFault
from pbccs_tpu.resilience.resources import (HostBudget, MemoryGovernor,
                                            OutputWriteError, parse_size,
                                            shape_bucket, split_sizes)

# ----------------------------------------------------------------- helpers


def make_chunk(zmw_id="m/1", n_reads=4, length=20):
    seq = np.arange(length, dtype=np.int8) % 4
    return Chunk(zmw_id,
                 [Subread(f"{zmw_id}/{i}", seq.copy())
                  for i in range(n_reads)],
                 np.full(4, 8.0))


def make_prep(zmw_id="m/1", tpl_len=24, n_reads=3):
    chunk = make_chunk(zmw_id, n_reads=n_reads, length=tpl_len)
    css = np.arange(tpl_len, dtype=np.int8) % 4
    mapped = [MappedRead(r.id, r.seq, 0, 0, tpl_len, True)
              for r in chunk.reads]
    return PreparedZmw(chunk, css, mapped, n_reads, 0, 1.5)


def fake_result(zmw_id, sequence="ACGT"):
    return ConsensusResult(
        id=zmw_id, sequence=sequence,
        qvs=np.full(len(sequence), 40.0), num_passes=4,
        predicted_accuracy=0.999, global_zscore=0.1, avg_zscore=0.2,
        zscores=np.array([0.5, np.nan]), status_counts=[2, 0, 1, 0, 0],
        mutations_tested=7, mutations_applied=3, snr=np.full(4, 8.0),
        elapsed_ms=1.25)


# ------------------------------------------------------------------- faults


class TestFaults:
    def test_parse_grammar(self):
        specs = faults.parse_faults(
            "polish.dispatch:error~m/3,prep.zmw:delay=0.5@2*1,"
            "checkpoint.record:corrupt%0.25")
        assert [s.site for s in specs] == ["polish.dispatch", "prep.zmw",
                                           "checkpoint.record"]
        assert specs[0].kind == "error" and specs[0].key == "m/3"
        assert specs[1].kind == "delay" and specs[1].delay_s == 0.5
        assert specs[1].at == 2 and specs[1].times == 1
        assert specs[2].kind == "corrupt" and specs[2].prob == 0.25

    @pytest.mark.parametrize("bad", ["nosite", "site:frobnicate",
                                     "site:error@x"])
    def test_parse_rejects(self, bad):
        with pytest.raises(FaultSpecError):
            faults.parse_faults(bad)

    def test_key_selects_poison(self):
        inj = faults.FaultInjector("polish.dispatch:error~m/2")
        inj.maybe_fail("polish.dispatch", keys=["m/1", "m/3"])  # no match
        inj.maybe_fail("other.site", keys=["m/2"])              # other site
        with pytest.raises(InjectedFault):
            inj.maybe_fail("polish.dispatch", keys=["m/1", "m/2"])
        assert inj.fired("polish.dispatch") == 1

    def test_at_and_times_modifiers(self):
        inj = faults.FaultInjector("s:error@2*1")
        inj.maybe_fail("s")                    # call 1: not yet
        with pytest.raises(InjectedFault):
            inj.maybe_fail("s")                # call 2: fires
        inj.maybe_fail("s")                    # call 3: exhausted
        assert inj.fired() == 1

    def test_probability_is_seed_deterministic(self):
        def fire_pattern(seed):
            inj = faults.FaultInjector("s:error%0.5", seed=seed)
            pat = []
            for _ in range(20):
                try:
                    inj.maybe_fail("s")
                    pat.append(0)
                except InjectedFault:
                    pat.append(1)
            return pat

        assert fire_pattern(7) == fire_pattern(7)
        assert fire_pattern(7) != fire_pattern(8)
        assert 0 < sum(fire_pattern(7)) < 20

    def test_corrupt_bytes_and_array(self):
        inj = faults.FaultInjector("c:corrupt")
        data = b"0123456789"
        bad = inj.corrupt("c", data)
        assert bad != data and len(bad) == len(data)
        arr = np.zeros(8, np.int8)
        bad_arr = inj.corrupt("c", arr)
        assert (bad_arr != arr).any()
        assert (arr == 0).all()  # input untouched
        # unarmed site is identity
        assert inj.corrupt("other", data) is data

    def test_module_level_noop_and_active_scope(self):
        faults.maybe_fail("anywhere", keys=["x"])  # no injector: no-op
        with faults.active("s:error"):
            with pytest.raises(InjectedFault):
                faults.maybe_fail("s")
        faults.maybe_fail("s")  # restored

    def test_injected_fault_metric(self):
        scope = default_registry().scope()
        with faults.active("s:error*2"):
            for _ in range(3):
                try:
                    faults.maybe_fail("s")
                except InjectedFault:
                    pass
        assert scope.counter_value("ccs_faults_injected_total",
                                   site="s", kind="error") == 2


# -------------------------------------------------------------------- retry


class TestRetry:
    def test_delays_backoff_and_cap(self):
        pol = retry.RetryPolicy(max_attempts=5, base_delay_s=0.1,
                                max_delay_s=0.3, multiplier=2.0,
                                jitter=0.0)
        assert list(pol.delays()) == [0.1, 0.2, 0.3, 0.3]

    def test_jitter_is_rng_deterministic(self):
        pol = retry.RetryPolicy(max_attempts=4, jitter=0.5)
        a = list(pol.delays(np.random.default_rng(3)))
        b = list(pol.delays(np.random.default_rng(3)))
        assert a == b
        assert a != list(pol.delays(np.random.default_rng(4)))

    def test_run_retries_then_succeeds(self):
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise RuntimeError("transient blip")
            return "ok"

        scope = default_registry().scope()
        pol = retry.RetryPolicy(max_attempts=4, base_delay_s=0.0)
        assert pol.run(flaky, retry_on=lambda e: "transient" in str(e),
                       site="test.retry") == "ok"
        assert len(calls) == 3
        assert scope.counter_value("ccs_retries_total",
                                   site="test.retry") == 2

    def test_run_propagates_non_retryable(self):
        pol = retry.RetryPolicy(max_attempts=5, base_delay_s=0.0)
        with pytest.raises(ValueError):
            pol.run(lambda: (_ for _ in ()).throw(ValueError("poison")),
                    retry_on=lambda e: False)

    def test_run_exhausts_with_cause(self):
        pol = retry.RetryPolicy(max_attempts=2, base_delay_s=0.0)
        with pytest.raises(retry.RetriesExhausted) as ei:
            pol.run(lambda: (_ for _ in ()).throw(RuntimeError("always")),
                    retry_on=lambda e: True)
        assert isinstance(ei.value.__cause__, RuntimeError)

    def test_deadline_bounds_total_wall(self):
        slept = []
        pol = retry.RetryPolicy(max_attempts=10, base_delay_s=5.0,
                                jitter=0.0, deadline_s=1.0)
        with pytest.raises(retry.RetriesExhausted):
            pol.run(lambda: (_ for _ in ()).throw(RuntimeError("x")),
                    retry_on=lambda e: True, sleep=slept.append)
        assert slept == []  # first 5 s backoff already busts the deadline

    def test_transient_classifier(self):
        # RESOURCE_EXHAUSTED is CAPACITY-shaped, never transient: a
        # same-shape retry of an OOM cannot succeed, so the adaptive
        # split path owns it (resilience.resources)
        assert not retry.is_transient_device_error(
            RuntimeError("RESOURCE_EXHAUSTED: out of memory"))
        assert resources.is_capacity_error(
            RuntimeError("RESOURCE_EXHAUSTED: out of memory"))
        assert retry.is_transient_device_error(
            RuntimeError("UNAVAILABLE: device preempted"))
        assert retry.is_transient_device_error(
            InjectedFault("polish.dispatch", "transient"))
        assert not retry.is_transient_device_error(
            ValueError("bad template"))
        assert not retry.is_transient_device_error(
            watchdog.WatchdogTimeout("polish.dispatch", 3.0))


# ----------------------------------------------------------------- watchdog


class TestWatchdog:
    def test_disabled_runs_inline(self):
        tid = threading.get_ident()
        assert watchdog.run_with_deadline(
            threading.get_ident, 0) == tid

    def test_result_and_exception_pass_through(self):
        assert watchdog.run_with_deadline(lambda: 42, 5.0) == 42
        with pytest.raises(ValueError):
            watchdog.run_with_deadline(
                lambda: (_ for _ in ()).throw(ValueError("boom")), 5.0)

    def test_timeout_raises_structured(self):
        scope = default_registry().scope()
        release = threading.Event()
        with pytest.raises(watchdog.WatchdogTimeout) as ei:
            watchdog.run_with_deadline(lambda: release.wait(30.0), 0.1,
                                       site="test.hang")
        release.set()  # unblock the abandoned thread
        assert ei.value.site == "test.hang"
        assert scope.counter_value("ccs_watchdog_timeouts_total",
                                   site="test.hang") == 1

    def test_configure_overrides_env(self):
        watchdog.configure(1.5)
        try:
            assert watchdog.default_deadline_s() == 1.5
        finally:
            watchdog.configure(None)
        assert os.environ.get("PBCCS_WATCHDOG_S") is None \
            or watchdog.default_deadline_s() >= 0


# --------------------------------------------------------------- quarantine


class TestQuarantineBisection:
    def run_isolate(self, n, poison_ids, settings=None):
        preps = [make_prep(f"m/{i}") for i in range(n)]
        dispatched = []

        def dispatch(sub):
            dispatched.append(len(sub))
            if any(p.chunk.id in poison_ids for p in sub):
                raise RuntimeError("poisoned sub-batch")
            return [(Failure.SUCCESS, fake_result(p.chunk.id))
                    for p in sub]

        def serial(prep, s, exc):
            if prep.chunk.id in poison_ids:
                return quarantine.quarantine_outcome(
                    prep, s or ConsensusSettings(), exc)
            return (Failure.SUCCESS, fake_result(prep.chunk.id))

        out = quarantine.isolate(
            preps, dispatch, settings or ConsensusSettings(),
            RuntimeError("batch failed"), serial_fn=serial)
        return out, dispatched

    def test_single_poison_isolated(self):
        out, dispatched = self.run_isolate(8, {"m/5"})
        assert [o[0] for o in out] == [Failure.SUCCESS] * 5 + \
            [Failure.OTHER] + [Failure.SUCCESS] * 2
        assert all(o[1].id == f"m/{i}" for i, o in enumerate(out)
                   if o[1] is not None)
        # log2 isolation: far fewer sub-dispatches than the serial O(n)
        assert len(dispatched) <= 2 * 3  # 2 halves per level, 3 levels

    def test_multiple_poisons(self):
        out, _ = self.run_isolate(8, {"m/0", "m/7"})
        statuses = [o[0] for o in out]
        assert statuses[0] == statuses[7] == Failure.OTHER
        assert statuses[1:7] == [Failure.SUCCESS] * 6

    def test_all_poison(self):
        out, _ = self.run_isolate(4, {f"m/{i}" for i in range(4)})
        assert all(o == (Failure.OTHER, None) for o in out)

    def test_degrade_emits_draft(self):
        out, _ = self.run_isolate(
            4, {"m/2"}, ConsensusSettings(degrade_quarantined=True))
        failure, result = out[2]
        assert failure == Failure.SUCCESS
        assert result.draft_only and result.id == "m/2"

    def test_quarantine_metrics(self):
        scope = default_registry().scope()
        self.run_isolate(8, {"m/3"})
        assert scope.counter_value("ccs_quarantined_zmws_total") == 1
        self.run_isolate(4, {"m/1"},
                         ConsensusSettings(degrade_quarantined=True))
        assert scope.counter_value("ccs_degraded_zmws_total") == 1


class TestSerialRescue:
    def test_persistent_hang_quarantines_not_stalls(self):
        """A ZMW whose polish hangs EVERY time (not just once) must end
        quarantined: the serial rescue runs under the same ambient
        watchdog deadline as the batch dispatch, so the run's last
        re-polish cannot stall forever."""
        prep = make_prep("m/0")
        # low SNR: the abandoned (hung) thread's eventual process_chunk
        # exits instantly at the SNR gate instead of polishing
        prep.chunk.snr = np.full(4, 1.0)
        watchdog.configure(0.2)
        try:
            with faults.active("polish.dispatch:delay=5~m/0"):
                t0 = time.monotonic()
                failure, result = quarantine.serial_rescue(
                    prep, ConsensusSettings(), RuntimeError("batch"))
                assert time.monotonic() - t0 < 2.0  # did not wait out 5 s
        finally:
            watchdog.configure(None)
        assert failure == Failure.OTHER and result is None


class TestDegradeToDraft:
    def test_draft_consensus_shape(self):
        prep = make_prep("m/9", tpl_len=16, n_reads=3)
        failure, result = quarantine.degrade_to_draft(
            prep, ConsensusSettings())
        assert failure == Failure.SUCCESS
        assert result.draft_only
        assert len(result.sequence) == 16
        assert (np.asarray(result.qvs) == quarantine.DRAFT_QV_CAP).all()
        assert result.num_passes == 3
        assert 0.89 < result.predicted_accuracy < 0.91
        assert np.isnan(result.global_zscore)


# --------------------------------------------------------------- checkpoint


class TestCheckpoint:
    def test_result_round_trip(self):
        r = fake_result("m/1", "ACGTA")
        back = checkpoint.result_from_json(
            json.loads(json.dumps(checkpoint.result_to_json(r))))
        assert back.id == r.id and back.sequence == r.sequence
        assert back.qualities == r.qualities
        np.testing.assert_array_equal(back.qvs, r.qvs)
        np.testing.assert_array_equal(back.status_counts, r.status_counts)
        # NaN z-scores survive
        assert np.isnan(back.zscores[1]) and back.zscores[0] == 0.5
        assert back.draft_only == r.draft_only

    def make_tally(self, ids):
        from pbccs_tpu.pipeline import ResultTally

        tally = ResultTally()
        for zid in ids:
            tally.tally(Failure.SUCCESS)
            tally.results.append(fake_result(zid))
        tally.tally(Failure.POOR_SNR)
        return tally

    def test_journal_round_trip(self, tmp_path):
        path = str(tmp_path / "j.ckpt")
        fp = {"version": 1, "inputs": [["a", 10]], "chunk_size": 2}
        j = checkpoint.CheckpointJournal(path)
        j.start(fp, resume=False)
        j.record_chunk(0, self.make_tally(["m/0", "m/1"]))
        j.record_chunk(1, self.make_tally(["m/2"]))
        j.close()

        restored = checkpoint.CheckpointJournal(path).load(fp)
        assert sorted(restored) == [0, 1]
        assert [r.id for r in restored[0].results] == ["m/0", "m/1"]
        assert restored[1].counts[Failure.SUCCESS] == 1
        assert restored[1].counts[Failure.POOR_SNR] == 1

    def test_fingerprint_mismatch_refuses(self, tmp_path):
        path = str(tmp_path / "j.ckpt")
        j = checkpoint.CheckpointJournal(path)
        j.start({"chunk_size": 2}, resume=False)
        j.record_chunk(0, self.make_tally(["m/0"]))
        j.close()
        assert checkpoint.CheckpointJournal(path).load(
            {"chunk_size": 4}) == {}

    def test_torn_and_corrupt_records_dropped(self, tmp_path):
        path = str(tmp_path / "j.ckpt")
        fp = {"chunk_size": 2}
        j = checkpoint.CheckpointJournal(path)
        j.start(fp, resume=False)
        j.record_chunk(0, self.make_tally(["m/0"]))
        j.record_chunk(1, self.make_tally(["m/1"]))
        j.close()
        # tear the LAST record mid-line (kill -9 mid-write)
        data = open(path, "rb").read()
        open(path, "wb").write(data[: data.rindex(b'{"type": "chunk"') + 40])
        scope = default_registry().scope()
        restored = checkpoint.CheckpointJournal(path).load(fp)
        assert sorted(restored) == [0]
        assert scope.counter_value("ccs_checkpoint_records_total",
                                   kind="corrupt") == 1

    def test_corrupt_fault_site(self, tmp_path):
        path = str(tmp_path / "j.ckpt")
        fp = {"chunk_size": 2}
        with faults.active("checkpoint.record:corrupt@2"):
            j = checkpoint.CheckpointJournal(path)
            j.start(fp, resume=False)              # record 1: header
            j.record_chunk(0, self.make_tally(["m/0"]))  # record 2: corrupt
            j.record_chunk(1, self.make_tally(["m/1"]))
            j.close()
        restored = checkpoint.CheckpointJournal(path).load(fp)
        assert sorted(restored) == [1]  # chunk 0 dropped, recomputable

    def test_fingerprint_tracks_same_size_content_change(self, tmp_path):
        """A regenerated same-size input must refuse the resume (mtime
        is part of the fingerprint): a refused resume only recomputes,
        a wrong splice silently mixes two datasets."""
        f = tmp_path / "in.fasta"
        f.write_text(">a\nACGT\n")
        fp1 = checkpoint.run_fingerprint([str(f)], 2, ConsensusSettings())
        os.utime(f, ns=(1, 1))  # same path + size, different mtime
        fp2 = checkpoint.run_fingerprint([str(f)], 2, ConsensusSettings())
        assert fp1 != fp2

    def test_resume_appends_and_last_record_wins(self, tmp_path):
        path = str(tmp_path / "j.ckpt")
        fp = {"chunk_size": 2}
        j = checkpoint.CheckpointJournal(path)
        j.start(fp, resume=False)
        j.record_chunk(0, self.make_tally(["m/0"]))
        j.close()
        j2 = checkpoint.CheckpointJournal(path)
        assert sorted(j2.load(fp)) == [0]
        j2.start(fp, resume=True)
        j2.record_chunk(0, self.make_tally(["m/0x"]))  # re-journal
        j2.record_chunk(1, self.make_tally(["m/1"]))
        j2.close()
        restored = checkpoint.CheckpointJournal(path).load(fp)
        assert sorted(restored) == [0, 1]
        assert [r.id for r in restored[0].results] == ["m/0x"]


# ---------------------------------------- resource-exhaustion governance


class TestCapacityClassification:
    def test_capacity_markers(self):
        assert resources.is_capacity_error(
            RuntimeError("RESOURCE_EXHAUSTED: Attempting to allocate"))
        assert resources.is_capacity_error(MemoryError())
        assert resources.is_capacity_error(
            RuntimeError("Out of memory allocating 2.1G in HBM"))
        assert resources.is_capacity_error(
            InjectedFault("sched.dispatch", "RESOURCE_EXHAUSTED"))
        assert not resources.is_capacity_error(ValueError("bad template"))
        assert not resources.is_capacity_error(
            RuntimeError("UNAVAILABLE: preempted"))

    def test_oom_fault_kind_is_capacity_not_transient(self):
        with faults.active("sched.dispatch:oom@1"):
            with pytest.raises(InjectedFault) as ei:
                faults.maybe_fail("sched.dispatch", keys=["cpu:0"])
        assert resources.is_capacity_error(ei.value)
        assert not retry.is_transient_device_error(ei.value)

    def test_enospc_fault_kind_raises_real_oserror(self):
        with faults.active("checkpoint.record:enospc@1"):
            with pytest.raises(OSError) as ei:
                faults.maybe_fail("checkpoint.record", keys=["chunk"])
        import errno

        assert ei.value.errno == errno.ENOSPC

    def test_grammar_accepts_new_kinds(self):
        specs = faults.parse_faults(
            "sched.dispatch:oom@1*1,output.write:enospc~bam@2")
        assert [s.kind for s in specs] == ["oom", "enospc"]
        with pytest.raises(FaultSpecError):
            faults.parse_faults("site:eNoSpC")


class TestMemoryGovernor:
    def test_ceiling_learn_and_apply(self):
        gov = MemoryGovernor()
        b = shape_bucket(128, 256, 8)
        assert gov.cap(b) is None
        assert gov.record_oom(b, 64, device="tpu:0") == 32
        assert gov.cap(b, device="tpu:0") == 32
        # a device with no own record inherits the fleet minimum
        # (pessimistic warm start, no per-device re-discovery)
        assert gov.cap(b, device="tpu:1") == 32
        assert gov.cap(b) == 32
        # ceilings only ever lower: a later SMALLER OOM tightens, a
        # later larger one cannot loosen
        assert gov.record_oom(b, 16, device="tpu:0") == 8
        assert gov.record_oom(b, 100, device="tpu:0") == 8
        assert gov.cap(b, device="tpu:0") == 8
        # an unrelated bucket is unaffected
        assert gov.cap(shape_bucket(64, 128, 4)) is None

    def test_ceiling_reset_on_device_readmit(self):
        gov = MemoryGovernor()
        b = shape_bucket(128, 256, 8)
        gov.record_oom(b, 64, device="tpu:0")
        gov.record_oom(b, 32, device="tpu:1")
        assert gov.reset_device("tpu:0") == 1
        # the re-admitted device re-learns; until then it inherits the
        # surviving fleet minimum
        assert gov.cap(b, device="tpu:0") == 16
        assert gov.reset_device("tpu:1") == 1
        assert gov.cap(b) is None
        assert gov.reset_device("tpu:1") == 0

    def test_split_sizes_greedy_minimizes_pow2_padding(self):
        # cap-sized parts are pow2 (a ceiling is Z//2 of a pow2
        # dispatch) and pad nothing; only the remainder is ragged
        assert split_sizes(10, 4) == [4, 4, 2]
        assert split_sizes(4, 4) == [4]
        assert split_sizes(5, 4) == [4, 1]
        assert split_sizes(12, 8) == [8, 4]
        assert split_sizes(1, 3) == [1]
        assert sum(split_sizes(1023, 64)) == 1023
        assert max(split_sizes(1023, 64)) == 64
        with pytest.raises(ValueError):
            split_sizes(4, 0)

    def test_device_scope_thread_local(self):
        assert resources.current_device() == "host"
        with resources.device_scope("tpu:3"):
            assert resources.current_device() == "tpu:3"
            seen = []
            t = threading.Thread(
                target=lambda: seen.append(resources.current_device()))
            t.start()
            t.join()
            assert seen == ["host"]   # scope never leaks across threads
        assert resources.current_device() == "host"


class TestHostBudget:
    def test_parse_size(self):
        assert parse_size("8G") == 8 << 30
        assert parse_size("512M") == 512 << 20
        assert parse_size("1.5K") == 1536
        assert parse_size("12345") == 12345
        assert parse_size("2GiB") == 2 << 30
        with pytest.raises(ValueError):
            parse_size("eight gigs")

    def test_gate_blocks_until_release(self):
        b = HostBudget(100)
        first = b.admit(80, site="t")
        got = []
        t = threading.Thread(
            target=lambda: got.append(b.admit(50, site="t")))
        t.start()
        time.sleep(0.15)
        assert not got                      # parked: 80 + 50 > 100
        first.release()
        t.join(timeout=5.0)
        assert got and got[0] is not None
        assert b.in_use() == 50
        assert b.throttle_count() == 1
        got[0].release()
        assert b.in_use() == 0

    def test_oversize_charge_admits_alone(self):
        b = HostBudget(10)
        lease = b.admit(500, site="t")
        assert lease is not None and b.in_use() == 500
        lease.release()

    def test_abort_unblocks_waiter(self):
        b = HostBudget(10)
        hold = b.admit(10, site="t")
        flag = threading.Event()
        got = []
        t = threading.Thread(
            target=lambda: got.append(
                b.admit(5, site="t", abort=flag.is_set)))
        t.start()
        time.sleep(0.1)
        flag.set()
        t.join(timeout=5.0)
        assert got == [None]                # aborted, nothing charged
        assert b.in_use() == 10
        hold.release()

    def test_release_idempotent(self):
        b = HostBudget(100)
        lease = b.admit(60, site="t")
        lease.release()
        lease.release()
        assert b.in_use() == 0

    def test_rejects_nonpositive_budget(self):
        with pytest.raises(ValueError):
            HostBudget(0)


class TestDiskFullWriters:
    def _records(self):
        from pbccs_tpu.io.bam import BamRecord

        return [BamRecord(name=f"m/{i}/ccs", seq="ACGTACGT",
                          qual="IIIIIIII", tags={"zm": i})
                for i in range(3)]

    def _write_all(self, path):
        from pbccs_tpu.io.bam import BamHeader, BamWriter, ReadGroupInfo

        header = BamHeader(read_groups=[ReadGroupInfo("m", "CCS")])
        with BamWriter(str(path), header) as bw:
            for rec in self._records():
                bw.write(rec)

    def test_bam_enospc_structured_and_rewrite_identical(self, tmp_path):
        control = tmp_path / "control.bam"
        self._write_all(control)
        out = tmp_path / "out.bam"
        scope = default_registry().scope()
        # header write is eligible call 1; fail on the 3rd write
        with faults.active("output.write:enospc@3*1"):
            with pytest.raises(OutputWriteError) as ei:
                self._write_all(out)
        assert ei.value.sink == "bam"
        import errno

        assert ei.value.errno == errno.ENOSPC
        # atomic: neither a torn output nor a leftover temp is published
        assert not out.exists()
        assert not (tmp_path / "out.bam.tmp").exists()
        assert scope.counter_value("ccs_output_write_errors_total",
                                   sink="bam") == 1
        # disk "freed": the rewrite is byte-identical to the control
        self._write_all(out)
        assert out.read_bytes() == control.read_bytes()

    def test_bam_body_exception_discards_tmp(self, tmp_path):
        from pbccs_tpu.io.bam import BamHeader, BamWriter, ReadGroupInfo

        out = tmp_path / "out.bam"
        with pytest.raises(RuntimeError, match="boom"):
            with BamWriter(str(out),
                           BamHeader(read_groups=[
                               ReadGroupInfo("m", "CCS")])) as bw:
                bw.write(self._records()[0])
                raise RuntimeError("boom")
        assert not out.exists()
        assert not (tmp_path / "out.bam.tmp").exists()

    def test_report_enospc_atomic(self, tmp_path):
        from pbccs_tpu.io.report import write_report_file
        from pbccs_tpu.pipeline import ResultTally

        tally = ResultTally()
        tally.tally(Failure.SUCCESS)
        path = tmp_path / "report.csv"
        with faults.active("output.write:enospc~report@1*1"):
            with pytest.raises(OutputWriteError) as ei:
                write_report_file(str(path), tally)
        assert ei.value.sink == "report"
        assert not path.exists()
        assert not (tmp_path / "report.csv.tmp").exists()
        write_report_file(str(path), tally)
        assert "Success -- CCS generated,1" in path.read_text()


class TestCheckpointDiskFull:
    def _tallies(self):
        from pbccs_tpu.pipeline import ResultTally

        out = []
        for i in range(3):
            t = ResultTally()
            t.tally(Failure.SUCCESS)
            t.results.append(fake_result(f"m/{i}"))
            out.append(t)
        return out

    def _restore_map(self, path, fp):
        restored = checkpoint.CheckpointJournal(str(path)).load(fp)
        return {i: [r.id for r in t.results] for i, t in restored.items()}

    def test_enospc_mid_record_then_resume_byte_identity(self, tmp_path):
        fp = {"v": 1}
        tallies = self._tallies()
        control = tmp_path / "control.ndjson"
        j = checkpoint.CheckpointJournal(str(control))
        j.start(fp, resume=False)
        for i, t in enumerate(tallies):
            j.record_chunk(i, t)
        j.close()
        want = self._restore_map(control, fp)

        path = tmp_path / "run.ndjson"
        j = checkpoint.CheckpointJournal(str(path))
        j.start(fp, resume=False)
        j.record_chunk(0, tallies[0])
        # disk fills while appending chunk 1: structured error with
        # bytes-written accounting, journal keeps its complete prefix
        with faults.active("checkpoint.record:enospc@1*1"):
            with pytest.raises(OutputWriteError) as ei:
                j.record_chunk(1, tallies[1])
        assert ei.value.sink == "checkpoint"
        # bytes-written accounting: exactly the durable prefix on disk
        assert ei.value.bytes_written == path.stat().st_size
        # emulate the short write a real ENOSPC leaves: a torn partial
        # line at the tail (no newline)
        with open(path, "ab") as fh:
            fh.write(b'{"type":"chunk","index":1,"cou')

        # space freed -> resume: the torn tail is dropped AND trimmed,
        # the rerun journals the missing chunks, and the final restore
        # set equals the uninterrupted run's
        j2 = checkpoint.CheckpointJournal(str(path))
        restored = j2.load(fp)
        assert sorted(restored) == [0]
        j2.start(fp, resume=True)
        for i in (1, 2):
            j2.record_chunk(i, tallies[i])
        j2.close()
        assert self._restore_map(path, fp) == want
        # every journal line parses (the torn tail did not concatenate
        # into the resumed records)
        for line in path.read_bytes().splitlines():
            json.loads(line)

    def test_close_reraise_does_not_clobber_structured_error(
            self, tmp_path):
        """A REAL full disk raises from flush() with bytes parked in
        the BufferedWriter; the teardown close() re-flushes and raises
        the same ENOSPC -- which must not replace the structured
        OutputWriteError with a raw OSError traceback."""
        import errno

        class FullDiskFile:
            def __init__(self, fh):
                self._fh = fh

            def write(self, data):       # buffers fine, like a real fd
                return len(data)

            def tell(self):
                return 0

            def flush(self):
                raise OSError(errno.ENOSPC, "No space left on device")

            def close(self):             # close re-flushes -> re-raises
                raise OSError(errno.ENOSPC, "No space left on device")

        path = tmp_path / "full.ndjson"
        j = checkpoint.CheckpointJournal(str(path))
        j.start({"v": 1}, resume=False)
        real_fh = j._fh
        j._fh = FullDiskFile(real_fh)
        try:
            with pytest.raises(OutputWriteError) as ei:
                j.record_chunk(0, self._tallies()[0])
        finally:
            real_fh.close()
        assert ei.value.sink == "checkpoint"
        assert j._fh is None             # handle dropped, journal kept

    def test_trim_noop_on_clean_journal(self, tmp_path):
        fp = {"v": 1}
        path = tmp_path / "clean.ndjson"
        j = checkpoint.CheckpointJournal(str(path))
        j.start(fp, resume=False)
        j.record_chunk(0, self._tallies()[0])
        j.close()
        before = path.read_bytes()
        j2 = checkpoint.CheckpointJournal(str(path))
        j2.load(fp)
        j2.start(fp, resume=True)
        j2.close()
        assert path.read_bytes() == before


class TestOomAdaptiveDispatch:
    """polish_prepared_batch's capacity governance, with the device
    dispatch stubbed: a RESOURCE_EXHAUSTED at batch size Z must split
    (pinned shapes, outcomes aligned), record a governor ceiling, and
    pre-split the NEXT batch for the bucket at admission -- never a
    same-shape retry loop, never quarantine of healthy ZMWs."""

    @pytest.fixture(autouse=True)
    def fresh_governor(self, monkeypatch):
        monkeypatch.setattr(resources, "_default_governor",
                            MemoryGovernor())

    def _preps(self, n):
        return [make_prep(f"m/{i}") for i in range(n)]

    def test_oom_splits_and_records_ceiling(self, monkeypatch):
        from pbccs_tpu import pipeline

        sizes = []

        def stub_dispatch(preps, settings, *, buckets=None, min_z=1,
                          prebaked=None):
            sizes.append(len(preps))
            if len(preps) > 2:
                raise RuntimeError("RESOURCE_EXHAUSTED: out of memory "
                                   "allocating scratch")
            return [(Failure.SUCCESS, None) for _ in preps]

        monkeypatch.setattr(pipeline, "_guarded_dispatch", stub_dispatch)
        scope = default_registry().scope()
        out = pipeline.polish_prepared_batch(self._preps(6))
        assert len(out) == 6
        assert all(f == Failure.SUCCESS for f, _ in out)
        # 6 OOMs -> 3+3, each OOMs -> 1+2, 2+1 -- no same-shape retry
        assert sizes[0] == 6 and max(sizes[1:]) <= 3
        assert scope.counter_value("ccs_resource_oom_splits_total") >= 1
        assert scope.counter_value("ccs_resource_oom_ceilings_total") >= 1
        gov = resources.default_governor()
        assert gov.snapshot()        # a ceiling was recorded
        # the NEXT batch for this bucket pre-splits at admission: no
        # dispatch bigger than the learned ceiling, no new OOM
        sizes.clear()
        out2 = pipeline.polish_prepared_batch(self._preps(6))
        assert len(out2) == 6
        assert max(sizes) <= 2
        assert scope.counter_value(
            "ccs_resource_presplit_batches_total") >= 1

    def test_oom_singleton_serial_rescue_not_retry(self, monkeypatch):
        from pbccs_tpu import pipeline

        rescued = []

        def stub_dispatch(preps, settings, **kw):
            raise RuntimeError("RESOURCE_EXHAUSTED: always")

        def stub_rescue(prep, settings, exc):
            rescued.append(prep.chunk.id)
            return (Failure.OTHER, None)

        monkeypatch.setattr(pipeline, "_guarded_dispatch", stub_dispatch)
        monkeypatch.setattr(quarantine, "serial_rescue", stub_rescue)
        out = pipeline.polish_prepared_batch(self._preps(4))
        assert len(out) == 4
        assert all(f == Failure.OTHER for f, _ in out)
        assert sorted(rescued) == [f"m/{i}" for i in range(4)]

    def test_injected_oom_at_polish_dispatch_splits(self, monkeypatch):
        """The fault grammar's oom kind at polish.dispatch drives the
        same path as a real device OOM: one split, zero quarantined."""
        from pbccs_tpu import pipeline

        sizes = []

        def spy(preps, settings, **kw):
            sizes.append(len(preps))
            return [(Failure.SUCCESS, None) for _ in preps]

        monkeypatch.setattr(pipeline, "_polish_batch_arrow", spy)
        scope = default_registry().scope()
        with faults.active("polish.dispatch:oom@1*1"):
            out = pipeline.polish_prepared_batch(self._preps(4))
        assert len(out) == 4
        assert all(f == Failure.SUCCESS for f, _ in out)
        assert sizes == [2, 2]      # split halves, no same-shape retry
        assert scope.counter_value("ccs_quarantined_zmws_total") == 0
        assert scope.counter_value("ccs_resource_oom_splits_total") == 1
        assert scope.counter_value(
            "ccs_retries_total", site="polish.dispatch") == 0


class TestPoolCapacityHandling:
    @pytest.fixture(autouse=True)
    def fresh_governor(self, monkeypatch):
        monkeypatch.setattr(resources, "_default_governor",
                            MemoryGovernor())

    def test_capacity_failure_requeues_same_device_no_strike(self):
        from pbccs_tpu.sched.pool import DevicePool

        bucket = shape_bucket(64, 128, 4)
        calls = []

        def flaky(device):
            calls.append(resources.current_device())
            if len(calls) == 1:
                raise RuntimeError("RESOURCE_EXHAUSTED: HBM full")
            return "ok"

        with DevicePool() as pool:
            fut = pool.submit("k", flaky, zmws=8, capacity_bucket=bucket)
            assert fut.result(timeout=30.0) == "ok"
            st = pool.status()
        # requeued to the SAME device, which was neither struck nor
        # benched (capacity != sick hardware)
        assert len(set(calls)) == 1 and len(calls) == 2
        assert st["devices"][0]["strikes"] == 0
        assert not st["devices"][0]["benched"]
        gov = resources.default_governor()
        assert gov.cap(bucket, device=calls[0]) == 4

    def test_injected_sched_oom_records_ceiling(self):
        from pbccs_tpu.sched.pool import DevicePool

        bucket = shape_bucket(64, 128, 4)
        scope = default_registry().scope()
        with faults.active("sched.dispatch:oom@1*1"):
            with DevicePool() as pool:
                fut = pool.submit("k", lambda device: "ok", zmws=6,
                                  capacity_bucket=bucket)
                assert fut.result(timeout=30.0) == "ok"
                st = pool.status()
        assert st["devices"][0]["strikes"] == 0
        assert scope.counter_value("ccs_resource_oom_splits_total") == 1
        assert scope.counter_value(
            "ccs_sched_device_benched_total",
            device=st["devices"][0]["device"]) == 0
        assert resources.default_governor().cap(bucket) == 3

    def test_capacity_without_bucket_stays_legacy(self):
        from pbccs_tpu.sched.pool import DevicePool

        def always_oom(device):
            raise RuntimeError("RESOURCE_EXHAUSTED: HBM full")

        with DevicePool() as pool:
            fut = pool.submit("k", always_oom, zmws=4)
            with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
                fut.result(timeout=30.0)
        assert resources.default_governor().snapshot() == {}


class TestBudgetedPipeline:
    def test_tight_budget_never_deadlocks(self, monkeypatch):
        """Regression: with prepare workers admitting out of sequence
        order and a budget that fits ~one batch, a release tied to
        ORDERED emission deadlocks (batch N+1's charge fills the budget
        while batch N's prep blocks in admit).  Leases release at
        polish completion, so the run must finish."""
        from pbccs_tpu import pipeline
        from pbccs_tpu.sched.executor import ScheduledPipeline
        from pbccs_tpu.sched.pool import DevicePool

        def stub_prepare(chunks, settings):
            from pbccs_tpu.pipeline import ResultTally

            time.sleep(0.01)
            return ResultTally(), [make_prep(c.id) for c in chunks]

        def stub_polish(preps, settings, **kw):
            time.sleep(0.02)
            return [(Failure.SUCCESS, fake_result(p.chunk.id))
                    for p in preps]

        monkeypatch.setattr(pipeline, "prepare_batch", stub_prepare)
        monkeypatch.setattr(pipeline, "polish_prepared_batch",
                            stub_polish)
        monkeypatch.setattr(pipeline, "prebake_polish",
                            lambda preps: None)
        # budget fits ONE batch's estimate (the deadlock-shaped config)
        from pbccs_tpu.parallel.batch import premarshal_nbytes

        (imax, jmax, r), z = pipeline._pinned_batch_shapes(
            [make_prep("m/0"), make_prep("m/1")], None, 1)
        budget = HostBudget(premarshal_nbytes((imax, jmax, r, z)) + 1)
        items = [(i, [make_chunk(f"m/{2 * i + k}") for k in range(2)],
                  None) for i in range(8)]
        with DevicePool() as pool:
            pipe = ScheduledPipeline(pool, ConsensusSettings(),
                                     prepare_workers=2, budget=budget)
            got = {}
            done = threading.Event()

            def consume():
                for idx, tally in pipe.run(iter(items)):
                    got[idx] = tally
                done.set()

            t = threading.Thread(target=consume, daemon=True)
            t.start()
            assert done.wait(timeout=60.0), \
                f"pipeline wedged with {len(got)}/8 batches emitted"
            t.join(timeout=5.0)
        assert sorted(got) == list(range(8))
        assert all(t.counts[Failure.SUCCESS] == 2 for t in got.values())
        assert budget.in_use() == 0   # every lease released


class TestEngineGovernedFlush:
    @pytest.fixture(autouse=True)
    def fresh_governor(self, monkeypatch):
        monkeypatch.setattr(resources, "_default_governor",
                            MemoryGovernor())

    def test_flush_pre_splits_at_learned_ceiling(self):
        """A serve flush for a bucket with a learned ceiling dispatches
        as ceiling-sized sub-batches (the fleet-wide conservative cap),
        before any device is picked."""
        from pbccs_tpu.serve.engine import CcsEngine, ServeConfig

        sizes = []

        def spy_polish(preps, settings):
            sizes.append(len(preps))
            return stub_polish(preps, settings)

        # the stub prep geometry: css 64 bases, no mapped reads
        bucket = shape_bucket(64, 128, 4)
        resources.default_governor().record_oom(bucket, 8, device="tpu:9")
        cfg = ServeConfig(max_batch=6, max_wait_ms=10.0)
        with CcsEngine(config=cfg, prep_fn=stub_prep,
                       polish_fn=spy_polish) as eng:
            reqs = [eng.submit(make_chunk(f"m/{i}")) for i in range(6)]
            for r in reqs:
                assert r.wait(10.0)
                assert r.failure == Failure.SUCCESS
        assert sizes and max(sizes) <= 4
        assert sum(sizes) == 6


# ------------------------------------------- serve: retry + watchdog wiring


def stub_prep(chunk, settings):
    return None, PreparedZmw(chunk, np.zeros(64, np.int8), [],
                             len(chunk.reads), 0, 0.0)


def stub_polish(preps, settings):
    return [(Failure.SUCCESS, fake_result(p.chunk.id)) for p in preps]


class TestServeResilience:
    def serve_stack(self, polish=stub_polish, **cfg):
        from pbccs_tpu.serve.engine import CcsEngine, ServeConfig
        from pbccs_tpu.serve.server import CcsServer

        eng = CcsEngine(config=ServeConfig(**cfg), prep_fn=stub_prep,
                        polish_fn=polish).start()
        srv = CcsServer(eng, port=0).start()
        return eng, srv

    def test_submit_with_retry_rides_out_overloaded(self):
        """Satellite contract: against a max_pending=1 engine, every
        submit_with_retry eventually succeeds -- the overloaded
        rejections are absorbed by the backoff policy."""
        from pbccs_tpu.serve.client import CcsClient

        def slow_polish(preps, settings):
            time.sleep(0.15)
            return stub_polish(preps, settings)

        eng, srv = self.serve_stack(polish=slow_polish, max_batch=1,
                                    max_wait_ms=10.0, max_pending=1)
        scope = default_registry().scope()
        try:
            with CcsClient(srv.host, srv.port) as cli:
                results = {}
                errs = []

                def one(i):
                    try:
                        msg = cli.submit_with_retry(
                            {"id": f"m/{i}",
                             "reads": [{"seq": "ACGTACGT"}] * 4},
                            policy=retry.RetryPolicy(
                                max_attempts=40, base_delay_s=0.05,
                                max_delay_s=0.2, deadline_s=30.0))
                        results[i] = msg["status"]
                    except Exception as e:  # noqa: BLE001
                        errs.append(repr(e))

                threads = [threading.Thread(target=one, args=(i,))
                           for i in range(4)]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join(timeout=60.0)
                assert not errs, errs
                assert results == {i: "Success" for i in range(4)}
                # max_pending=1 forces real rejections along the way
                assert scope.counter_value("ccs_retries_total",
                                           site="client.submit") >= 1
        finally:
            srv.shutdown()
            eng.close()

    def test_engine_watchdog_fails_batch_keeps_serving(self):
        from pbccs_tpu.serve.engine import CcsEngine, ServeConfig

        hang = threading.Event()

        def hung_once(preps, settings):
            if not hang.is_set():
                hang.set()
                time.sleep(5.0)
            return stub_polish(preps, settings)

        cfg = ServeConfig(max_batch=1, max_wait_ms=60_000.0,
                          polish_timeout_ms=200.0)
        with CcsEngine(config=cfg, prep_fn=stub_prep,
                       polish_fn=hung_once) as eng:
            bad = eng.submit(make_chunk("m/hang"))
            assert bad.wait(10.0)
            assert bad.error is not None and "watchdog" in bad.error
            ok = eng.submit(make_chunk("m/2"))
            assert ok.wait(10.0)
            assert ok.failure == Failure.SUCCESS
            assert eng.status()["errors"] == 1


# ------------------------------------- pipeline: batch-fallback parity (e2e)


@pytest.mark.slow
@pytest.mark.parametrize("on_error", ["bisect", "serial"])
def test_poisoned_batch_survivor_parity(rng, on_error):
    """A poisoned batch yields byte-identical results for all surviving
    ZMWs vs an unpoisoned run -- for the bisection path AND the legacy
    serial path (the satellite contract; chaos_smoke re-checks this in
    tier-1 CI)."""
    from pbccs_tpu.pipeline import process_chunks
    from pbccs_tpu.simulate import simulate_zmw

    chunks = []
    for i in range(5):
        _, reads, _, snr = simulate_zmw(rng, 60, 4)
        chunks.append(Chunk(
            f"par/{i}",
            [Subread(f"par/{i}/{k}", r) for k, r in enumerate(reads)],
            snr))
    base = process_chunks(list(chunks))
    base_out = {r.id: (r.sequence, r.qualities) for r in base.results}

    with faults.active("polish.dispatch:error~par/1"):
        pois = process_chunks(list(chunks), on_error=on_error)
    pois_out = {r.id: (r.sequence, r.qualities) for r in pois.results}
    assert pois_out == {k: v for k, v in base_out.items() if k != "par/1"}
    assert pois.counts[Failure.OTHER] == 1
    assert pois.total == base.total
