"""tools/trace_merge.py hardening: empty bundles, zero-span replicas,
missing wall-clock origins, and alien event shapes must merge with a
note -- never a KeyError mid-merge (the fleet smoke feeds this tool
real trace-stop bundles; chaos feeds it torn ones)."""

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_REPO, "tools"))

import trace_merge  # noqa: E402  (tools/ module, path-injected above)


def chrome(events, origin=None, **meta):
    doc = {"traceEvents": events, "meta": dict(meta)}
    if origin is not None:
        doc["meta"]["origin_unix"] = origin
    return doc


def span(name, ts, span_id=None, parent=None, remote_parent=None,
         trace_id=None, **extra):
    args = dict(extra)
    if span_id is not None:
        args["span_id"] = span_id
    if parent is not None:
        args["parent"] = parent
    if remote_parent is not None:
        args["remote_parent"] = remote_parent
    if trace_id is not None:
        args["trace_id"] = trace_id
    return {"ph": "X", "name": name, "ts": ts, "dur": 5.0, "tid": 0,
            "args": args}


class TestMergeDegradation:
    def test_empty_bundle_merges_to_valid_empty_doc(self):
        merged = trace_merge.merge_docs(
            trace_merge.expand_bundle({"replicas": {}}))
        assert merged["traceEvents"][0]["name"] == "process_name"
        assert merged["meta"]["processes"] == {"router": 1}
        assert trace_merge.request_trees(merged) == {}

    def test_totally_empty_input(self):
        merged = trace_merge.merge_docs([])
        assert merged["traceEvents"] == []
        assert merged["meta"]["processes"] == {}

    def test_zero_span_replica_merges_cleanly(self):
        bundle = {"trace": chrome([span("a", 1.0, trace_id="t1",
                                        span_id="s1")], origin=100.0),
                  "replicas": {"r:1": chrome([], origin=100.5)}}
        merged = trace_merge.merge_docs(trace_merge.expand_bundle(bundle))
        assert merged["meta"]["processes"] == {"router": 1,
                                               "replica r:1": 2}
        report = trace_merge.request_trees(merged)
        assert report["t1"]["events"] == 1

    def test_missing_origin_is_noted_not_keyerror(self):
        bundle = {"trace": chrome([span("a", 1.0)], origin=100.0),
                  "replicas": {"r:1": chrome([span("b", 2.0)])}}
        merged = trace_merge.merge_docs(trace_merge.expand_bundle(bundle))
        assert merged["meta"]["unrebased_processes"] == ["replica r:1"]
        # the unrebased process's events keep their own timebase
        names = {ev.get("name") for ev in merged["traceEvents"]}
        assert {"a", "b"} <= names

    def test_malformed_replica_chrome_is_skipped_with_note(self):
        bundle = {"trace": chrome([span("a", 1.0)], origin=1.0),
                  "replicas": {"bad:1": None, "worse:2": "not a dict",
                               "ok:3": chrome([span("c", 3.0)],
                                              origin=1.5)}}
        merged = trace_merge.merge_docs(trace_merge.expand_bundle(bundle))
        assert sorted(merged["meta"]["skipped_processes"]) == [
            "replica bad:1", "replica worse:2"]
        assert "replica ok:3" in merged["meta"]["processes"]

    def test_alien_event_shapes_never_raise(self):
        doc = chrome([
            {"ph": "X", "name": "no_args", "ts": 1.0},       # args absent
            {"ph": "X", "name": "bad_args", "ts": 2.0,
             "args": "not a dict"},
            "not even a dict",
            {"ph": "X", "name": "ok", "ts": 3.0,
             "args": {"trace_id": "t", "span_id": "s"}},
        ], origin=5.0)
        merged = trace_merge.merge_docs([("p", doc)])
        report = trace_merge.request_trees(merged)
        assert report["t"]["events"] == 1
        assert trace_merge.trace_connected(merged, "t")

    def test_mixed_type_trace_ids_skip_not_typeerror(self):
        doc = chrome([
            span("alien", 1.0, trace_id=42),          # int id: skipped
            span("ok", 2.0, trace_id="t1", span_id="s1"),
        ], origin=1.0)
        merged = trace_merge.merge_docs([("p", doc)])
        report = trace_merge.request_trees(merged)
        assert list(report) == ["t1"]
        assert report["t1"]["events"] == 1

    def test_alien_name_and_unhashable_id_skip_not_typeerror(self):
        doc = chrome([
            {"ph": "X", "name": 5, "ts": 1.0,       # non-string name
             "args": {"span_id": "s1", "trace_id": "t1"}},
            {"ph": "X", "name": "ok", "ts": 2.0,
             "id": ["unhashable"],                   # alien event id
             "args": {"trace_id": "t1", "parent": ["also"],
                      "span_id": "s2"}},
        ], origin=1.0)
        merged = trace_merge.merge_docs([("p", doc)])
        report = trace_merge.request_trees(merged)
        assert report["t1"]["events"] == 2
        assert report["t1"]["spans"] == ["5", "ok"]

    def test_non_numeric_meta_counts_degrade(self):
        doc = chrome([span("a", 1.0)], origin=1.0,
                     dropped_spans="garbage", open_spans=None)
        merged = trace_merge.merge_docs([("p", doc)])
        assert merged["meta"]["dropped_spans"] == 0

    def test_cross_process_links_still_connect_after_hardening(self):
        bundle = {
            "trace": chrome([span("router.request", 1.0, span_id="rt-1",
                                  trace_id="t1")], origin=100.0),
            "replicas": {"r:1": chrome(
                [span("serve.prep", 2.0, span_id="sp-1",
                      remote_parent="rt-1", trace_id="t1")],
                origin=100.2)},
        }
        merged = trace_merge.merge_docs(trace_merge.expand_bundle(bundle))
        assert trace_merge.trace_connected(merged, "t1")
        report = trace_merge.request_trees(merged)
        assert report["t1"]["processes"] == [1, 2]
