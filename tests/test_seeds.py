"""Seed finding + SDP chaining tests.

Golden expectations from reference tests/TestSparseAlign.cpp (exact /
partial / inserted / divergent pairs: chain length and endpoint checks)
plus unit tests of the hash/mask layers and the band closure.
"""

import numpy as np

from pbccs_tpu.align.seeds import (
    anchor_bands,
    chain_seeds,
    find_seeds,
    kmer_hashes,
    sparse_align,
)
from pbccs_tpu.models.arrow.params import encode_bases

S1 = "ACGTACACACAGTACAGTACAAGTTTCACGGACATTTGGTTCCCACTTGTACAGTGCACACGGGTTACACGT"


class TestKmerHashes:
    def test_distinct_and_positional(self):
        h = kmer_hashes(encode_bases("ACGTACGT"), 4)
        assert len(h) == 5
        assert h[0] == h[4]  # ACGT == ACGT
        assert len(set(h.tolist())) == 4

    def test_pad_masks(self):
        codes = encode_bases("ACGT")
        codes = np.concatenate([codes, [4], codes])
        h = kmer_hashes(codes, 4)
        assert (h[1:4] == -1).all()
        assert h[0] >= 0 and h[5] >= 0

    def test_short_input(self):
        assert len(kmer_hashes(encode_bases("AC"), 5)) == 0


class TestFindSeeds:
    def test_homopolymer_masked(self):
        s = encode_bases("AAAAAAAA")
        assert len(find_seeds(s, s, 5)) == 0

    def test_self_match(self):
        s = encode_bases(S1)
        seeds = find_seeds(s, s, 5)
        # every position matches itself (plus off-diagonal repeats)
        diag = seeds[seeds[:, 0] == seeds[:, 1]]
        assert len(diag) == len(S1) - 5 + 1


class TestChain:
    def test_exact_align(self):
        s = encode_bases(S1)
        chain = sparse_align(s, s, 5)
        assert len(chain) == len(S1) - 5 + 1
        assert tuple(chain[0]) == (0, 0)
        assert tuple(chain[-1]) == (len(S1) - 5, len(S1) - 5)

    def test_exact_partial(self):
        s2 = "TTTGGTTCCCACTTGTACAGTGCACACGGGTTACACGT"
        chain = sparse_align(encode_bases(S1), encode_bases(s2), 5)
        assert len(chain) == len(s2) - 5 + 1
        assert tuple(chain[0]) == (34, 0)
        assert tuple(chain[-1]) == (len(S1) - 5, len(s2) - 5)

    def test_insert_align(self):
        s2 = ("ACGTACACACAGTACAGTACAAGTTTCACGGACAT" + "A" * 39 +
              "TTGGTTCCCACTTGTACAGTGCACACGGGTTACACGT")
        chain = sparse_align(encode_bases(S1), encode_bases(s2), 5)
        assert tuple(chain[0]) == (0, 0)
        assert tuple(chain[-1]) == (len(S1) - 5, len(s2) - 5)

    def test_no_align(self):
        s2 = "AAAATCCCCCCCCCCAGGGGG"
        chain = sparse_align(encode_bases(S1), encode_bases(s2), 5)
        assert len(chain) == 0

    def test_divergent_align(self):
        s2 = ("ACGTACACCAGTAAGTACAAGTTTCACGCGAATTTGGTTCCCACTTGTCAAGTGCACAC"
              "GGGTTACACGT")
        chain = sparse_align(encode_bases(S1), encode_bases(s2), 5)
        assert tuple(chain[0]) == (0, 0)
        assert tuple(chain[-1]) == (len(S1) - 5, len(s2) - 5)

    def test_chain_monotone(self, rng):
        bases = np.arange(4, dtype=np.int8)
        s1 = rng.choice(bases, 400).astype(np.int8)
        # derive s2 by point mutations
        s2 = s1.copy()
        for p in rng.integers(0, 400, 30):
            s2[p] = (s2[p] + 1) % 4
        chain = sparse_align(s1, s2, 6)
        assert len(chain) > 10
        assert (np.diff(chain[:, 0]) > 0).all()
        assert (np.diff(chain[:, 1]) > 0).all()


class TestAnchorBands:
    def test_bands_cover_anchors(self):
        chain = np.array([[10, 12], [50, 49], [90, 95]], np.int32)
        bands = anchor_bands(chain, 100, 120, width=5)
        assert bands.shape == (100, 2)
        for i, j in chain:
            assert bands[i, 0] <= max(j - 5, 0)
            assert bands[i, 1] >= min(j + 5, 120)
        # monotone, nonempty
        assert (bands[:, 1] > bands[:, 0]).all()
        assert (np.diff(bands[:, 0]) >= 0).all()
        assert (np.diff(bands[:, 1]) >= 0).all()

    def test_no_anchors_full_band(self):
        bands = anchor_bands(np.zeros((0, 2), np.int32), 10, 20)
        assert (bands[:, 0] == 0).all()
        assert (bands[:, 1] == 20).all()
