"""Multi-tenant edge tests: token auth, TLS, fair queuing, SLO shedding.

Unit layers (no sockets): TenantDirectory parsing, the resolve_tenant
spoofing rule, FairQueue's weighted-DRR admission, the BurnMeter, and
RetryPolicy's server-hint backoff.  Socket layers: an authenticated
`ccs serve` front door (missing/bad token, spoofing, TLS handshake
aborts), the router tier (link-token injection, tenant forwarding,
quota/queue/shed verdicts), and the fleet wiring (child serve args,
authenticated health probes, the fleet admin verb behind auth).
"""

import json
import socket
import ssl
import subprocess
import threading
import time

import pytest

from pbccs_tpu.obs.metrics import MeasurementScope, default_registry
from pbccs_tpu.resilience.retry import RetriesExhausted, RetryPolicy
from pbccs_tpu.serve import protocol, tenancy
from pbccs_tpu.serve.client import CcsClient, ServeError
from pbccs_tpu.serve.router import CcsRouter, RouterConfig, RouterServer
from pbccs_tpu.serve.server import CcsServer
from pbccs_tpu.serve.supervisor import build_fleet_parser, child_serve_args
from pbccs_tpu.serve.tenancy import (
    BurnMeter,
    FairQueue,
    Tenant,
    TenantDirectory,
    resolve_tenant,
)
from tests.test_router import ZMW, FakeReplica, wait_until
from tests.test_serve import stub_engine

# ---------------------------------------------------------------- helpers


def directory(*tenants):
    return TenantDirectory(list(tenants))


def edge_directory():
    """The serve-tier cast: two ordinary tenants + the trusted router."""
    return directory(
        Tenant("alpha", "tok-alpha"),
        Tenant("beta", "tok-beta"),
        Tenant("_router", "tok-router", priority=0, trusted=True))


def router_directory():
    """The router-tier cast: a quota-1 flooder, a weighted neighbor, a
    never-shed priority-0 tenant, and the trusted link identity."""
    return directory(
        Tenant("alpha", "tok-alpha", max_inflight=1, priority=1),
        Tenant("beta", "tok-beta", max_inflight=8, priority=1, weight=2),
        Tenant("gold", "tok-gold", max_inflight=8, priority=0),
        Tenant("_router", "tok-router", priority=0, trusted=True))


def wire_call(port, frames, n_replies=1, timeout=5.0):
    """Raw NDJSON exchange: send `frames`, read `n_replies` replies."""
    with socket.create_connection(("127.0.0.1", port), timeout=timeout) as s:
        s.settimeout(timeout)
        for f in frames:
            s.sendall(protocol.encode_msg(f))
        rf = s.makefile("rb")
        return [protocol.decode_line(rf.readline()) for _ in range(n_replies)]


@pytest.fixture(scope="session")
def tls_certs(tmp_path_factory):
    """Self-signed EC cert (its own CA: issuer == subject)."""
    d = tmp_path_factory.mktemp("tls")
    cert, key = str(d / "cert.pem"), str(d / "key.pem")
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "ec", "-pkeyopt",
         "ec_paramgen_curve:prime256v1", "-nodes", "-keyout", key,
         "-out", cert, "-days", "2", "-subj", "/CN=localhost"],
        check=True, capture_output=True)
    return cert, key


# ------------------------------------------------------------- token file


class TestTokenFile:
    def write(self, tmp_path, doc):
        p = tmp_path / "tokens.json"
        p.write_text(doc if isinstance(doc, str) else json.dumps(doc))
        return str(p)

    def test_parse_defaults_and_overrides(self, tmp_path):
        d = TenantDirectory.from_file(self.write(tmp_path, {"tenants": [
            {"name": "a", "token": "ta"},
            {"name": "r", "token": "tr", "max_inflight": 2, "priority": 0,
             "weight": 3, "trusted": True}]}))
        a, r = d.get("a"), d.get("r")
        assert (a.max_inflight, a.priority, a.weight, a.trusted) == \
            (8, 1, 1, False)
        assert (r.max_inflight, r.priority, r.weight, r.trusted) == \
            (2, 0, 3, True)
        assert d.authenticate("ta") is a
        assert d.authenticate("nope") is None
        assert d.authenticate("") is None
        assert d.authenticate(42) is None
        assert d.authenticate("x" * (tenancy.TOKEN_MAX_CHARS + 1)) is None

    @pytest.mark.parametrize("doc", [
        "not json",
        {"tenants": {}},
        {"tenants": ["row"]},
        {"tenants": [{"token": "t"}]},
        {"tenants": [{"name": "a"}]},
        {"tenants": [{"name": "a", "token": ""}]},
        {"tenants": [{"name": "a", "token": "x" * 300}]},
        {"tenants": [{"name": "a", "token": "t", "max_inflight": 0}]},
        {"tenants": [{"name": "a", "token": "t", "priority": -1}]},
        {"tenants": [{"name": "a", "token": "t", "weight": 0}]},
        {"tenants": [{"name": "a", "token": "t", "trusted": "yes"}]},
        {"tenants": [{"name": "a", "token": "t", "priority": True}]},
        {"tenants": []},
        {"tenants": [{"name": "a", "token": "t"},
                     {"name": "a", "token": "u"}]},
        {"tenants": [{"name": "a", "token": "t"},
                     {"name": "b", "token": "t"}]},
    ])
    def test_malformed_files_raise(self, tmp_path, doc):
        with pytest.raises(ValueError):
            TenantDirectory.from_file(self.write(tmp_path, doc))

    def test_resolve_tenant_spoofing_rule(self):
        alpha = Tenant("alpha", "ta")
        router = Tenant("_router", "tr", trusted=True)
        # open front door: no identity at all
        assert resolve_tenant(None, {"name": "beta"}) is None
        # an ordinary tenant cannot impersonate another
        assert resolve_tenant(alpha, {"name": "beta"}) == "alpha"
        # the trusted link forwards the original submitter
        assert resolve_tenant(router, {"name": "beta"}) == "beta"
        assert resolve_tenant(router, None) == "_router"


# ------------------------------------------------------------- fair queue


class TestFairQueue:
    def test_admission_verdicts(self):
        fq = FairQueue(directory(Tenant("a", "t", max_inflight=1)),
                       queue_depth=2)
        assert fq.try_admit("a", "r1") == "dispatch"
        assert fq.try_admit("a", "r2") == "queued"
        assert fq.try_admit("a", "r3") == "queued"
        assert fq.try_admit("a", "r4") == "rejected"
        # nothing fits while the slot is held
        assert fq.drain() == []
        fq.complete("a")
        assert fq.drain() == [("a", "r2")]
        row = fq.rows()[0]
        assert (row["inflight"], row["queued"], row["completed"],
                row["queued_total"], row["rejected"]) == (1, 1, 1, 2, 1)

    def test_weighted_drr_drain_order(self):
        fq = FairQueue(directory(Tenant("a", "ta", max_inflight=99),
                                 Tenant("b", "tb", max_inflight=99,
                                        weight=2)),
                       queue_depth=99, quantum=1)
        # park a backlog directly (quota high, so drain order is pure DRR)
        for st in fq._states.values():
            st.inflight = st.tenant.max_inflight
        for i in range(6):
            assert fq.try_admit("a", f"a{i}") == "queued"
            assert fq.try_admit("b", f"b{i}") == "queued"
        for st in fq._states.values():
            st.inflight = 0
        order = [name for name, _ in fq.drain()]
        # weight 2 drains twice per round: a,b,b repeating
        assert order[:6] == ["a", "b", "b", "a", "b", "b"]
        assert order.count("a") == 6 and order.count("b") == 6

    def test_flush_empties_queues(self):
        fq = FairQueue(directory(Tenant("a", "t", max_inflight=1)),
                       queue_depth=8)
        fq.try_admit("a", "r1")
        fq.try_admit("a", "r2")
        fq.try_admit("a", "r3")
        assert fq.flush() == [("a", "r2"), ("a", "r3")]
        assert fq.rows()[0]["queued"] == 0

    def test_shed_accounting(self):
        fq = FairQueue(directory(Tenant("a", "t")))
        fq.record_shed("a")
        fq.record_shed("a")
        assert fq.rows()[0]["shed"] == 2


class TestBurnMeter:
    def test_rate_from_deltas(self):
        clock = [0.0]
        m = BurnMeter(window_s=30.0, clock=lambda: clock[0])
        assert m.rate() == 0.0
        m.observe("r1", {"requests": 0, "violations": 0})
        m.observe("r1", {"requests": 10, "violations": 4})
        assert m.rate() == pytest.approx(0.4)
        m.observe("r1", {"requests": 20, "violations": 4})
        assert m.rate() == pytest.approx(0.2)

    def test_window_expiry(self):
        clock = [0.0]
        m = BurnMeter(window_s=10.0, clock=lambda: clock[0])
        m.observe("r1", {"requests": 0, "violations": 0})
        m.observe("r1", {"requests": 10, "violations": 10})
        assert m.rate() == 1.0
        clock[0] = 11.0
        assert m.rate() == 0.0

    def test_restart_rebaselines(self):
        clock = [0.0]
        m = BurnMeter(window_s=30.0, clock=lambda: clock[0])
        m.observe("r1", {"requests": 0, "violations": 0})
        m.observe("r1", {"requests": 100, "violations": 0})
        # counters moved backwards: a restart, not -98 violations
        m.observe("r1", {"requests": 2, "violations": 1})
        assert m.rate() == 0.0
        m.observe("r1", {"requests": 4, "violations": 2})
        assert m.rate() == pytest.approx(1 / 102)

    def test_malformed_slo_ignored(self):
        m = BurnMeter()
        m.observe("r1", None)
        m.observe("r1", "slo")
        m.observe("r1", {"requests": "many", "violations": 1})
        assert m.rate() == 0.0


# ------------------------------------------------------------ retry hints


class TestRetryHint:
    def run_failing(self, policy, hint):
        sleeps = []

        def boom():
            raise RuntimeError("shed")

        with pytest.raises(RetriesExhausted):
            policy.run(boom, retry_on=lambda e: True, sleep=sleeps.append,
                       delay_hint=lambda e: hint)
        return sleeps

    def test_hint_overrides_schedule(self):
        policy = RetryPolicy(max_attempts=3, base_delay_s=0.001,
                             max_delay_s=0.5, jitter=0.0)
        assert self.run_failing(policy, 0.2) == [0.2, 0.2]

    def test_hostile_hint_capped(self):
        policy = RetryPolicy(max_attempts=3, base_delay_s=0.001,
                             max_delay_s=0.5, jitter=0.0)
        assert self.run_failing(policy, 3600.0) == [0.5, 0.5]

    def test_no_hint_keeps_exponential(self):
        policy = RetryPolicy(max_attempts=3, base_delay_s=0.001,
                             max_delay_s=0.5, jitter=0.0)
        assert self.run_failing(policy, None) == \
            pytest.approx([0.001, 0.002])


# ------------------------------------------------- authenticated ccs serve


@pytest.fixture
def auth_stack():
    eng = stub_engine(max_batch=2, max_wait_ms=20.0, max_pending=16)
    eng.start()
    srv = CcsServer(eng, port=0, tenants=edge_directory())
    srv.start()
    yield srv
    srv.shutdown()
    eng.close()


class TestServeAuth:
    def test_missing_token_rejected_session_survives(self, auth_stack):
        scope = MeasurementScope(default_registry())
        frames = [
            {"verb": "submit", "id": "s1", "zmw": ZMW},
            {"verb": "submit", "id": "s2", "zmw": ZMW,
             "auth": "tok-alpha"},
        ]
        r1, r2 = wire_call(auth_stack.port, frames, n_replies=2)
        assert r1["type"] == "error"
        assert r1["code"] == protocol.ERR_UNAUTHORIZED
        assert r1["id"] == "s1"
        # the same session works once it presents the token
        assert r2["type"] == "result" and r2["id"] == "s2"
        assert scope.counter_value("ccs_tenant_auth_failures_total",
                                   reason="missing_token") == 1

    def test_bad_token_rejected(self, auth_stack):
        scope = MeasurementScope(default_registry())
        (r,) = wire_call(auth_stack.port,
                         [{"verb": "status", "id": "s1",
                           "auth": "tok-wrong"}])
        assert r["code"] == protocol.ERR_UNAUTHORIZED
        assert scope.counter_value("ccs_tenant_auth_failures_total",
                                   reason="bad_token") == 1

    def test_every_verb_is_gated(self, auth_stack):
        for verb in ("status", "metrics", "ping", "submit"):
            (r,) = wire_call(auth_stack.port,
                             [{"verb": verb, "id": "x", "zmw": ZMW}])
            assert r["code"] == protocol.ERR_UNAUTHORIZED, verb

    def test_client_auth_token_rides_every_frame(self, auth_stack):
        scope = MeasurementScope(default_registry())
        with CcsClient("127.0.0.1", auth_stack.port,
                       auth_token="tok-alpha") as cli:
            reply = cli.submit("m/77", ["ACGTACGT"] * 4).reply(10.0)
            assert reply["type"] == "result"
            assert cli.status(10.0)["type"] == "status"
        assert scope.counter_value("ccs_tenant_requests_total",
                                   tenant="alpha") == 1

    def test_untrusted_tenant_field_ignored(self, auth_stack):
        scope = MeasurementScope(default_registry())
        (r,) = wire_call(auth_stack.port,
                         [{"verb": "submit", "id": "s1", "zmw": ZMW,
                           "auth": "tok-alpha",
                           "tenant": {"name": "beta"}}])
        assert r["type"] == "result"
        # attributed to the TOKEN's tenant, not the spoofed wire field
        assert scope.counter_value("ccs_tenant_requests_total",
                                   tenant="alpha") == 1
        assert scope.counter_value("ccs_tenant_requests_total",
                                   tenant="beta") == 0

    def test_trusted_token_forwards_tenant(self, auth_stack):
        scope = MeasurementScope(default_registry())
        (r,) = wire_call(auth_stack.port,
                         [{"verb": "submit", "id": "s1", "zmw": ZMW,
                           "auth": "tok-router",
                           "tenant": {"name": "beta"}}])
        assert r["type"] == "result"
        assert scope.counter_value("ccs_tenant_requests_total",
                                   tenant="beta") == 1


class TestServeTLS:
    @pytest.fixture
    def tls_stack(self, tls_certs):
        cert, key = tls_certs
        eng = stub_engine(max_batch=2, max_wait_ms=20.0, max_pending=16)
        eng.start()
        srv = CcsServer(eng, port=0,
                        ssl_context=tenancy.server_ssl_context(cert, key))
        srv.start()
        yield srv, cert
        srv.shutdown()
        eng.close()

    def test_tls_round_trip(self, tls_stack):
        srv, cert = tls_stack
        with CcsClient("127.0.0.1", srv.port, tls_ca=cert) as cli:
            reply = cli.submit("m/1", ["ACGTACGT"] * 4).reply(10.0)
            assert reply["type"] == "result"

    def test_plaintext_client_aborts_cleanly(self, tls_stack):
        srv, cert = tls_stack
        scope = MeasurementScope(default_registry())
        with socket.create_connection(("127.0.0.1", srv.port),
                                      timeout=5.0) as s:
            s.settimeout(5.0)
            s.sendall(protocol.encode_msg(
                {"verb": "status", "id": "s1"}))
            # the handshake fails server-side; no frame is ever
            # accepted -- the socket just dies (FIN or RST)
            try:
                assert s.recv(4096) == b""
            except OSError:
                pass
        assert wait_until(lambda: scope.counter_value(
            "ccs_serve_session_aborts_total", cause="tls_handshake") == 1)
        # the listener survives for real TLS clients
        with CcsClient("127.0.0.1", srv.port, tls_ca=cert) as cli:
            assert cli.status(10.0)["type"] == "status"

    def test_wrong_ca_rejected_client_side(self, tls_stack, tmp_path):
        srv, _cert = tls_stack
        other = tmp_path / "other-ca.pem"
        subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "ec", "-pkeyopt",
             "ec_paramgen_curve:prime256v1", "-nodes", "-keyout",
             str(tmp_path / "other-key.pem"), "-out", str(other),
             "-days", "2", "-subj", "/CN=evil"],
            check=True, capture_output=True)
        with pytest.raises(ConnectionError, match="TLS handshake failed"):
            CcsClient("127.0.0.1", srv.port, tls_ca=str(other))

    def test_metrics_endpoint_tls_only(self, tls_certs):
        from pbccs_tpu.obs.httpexp import start_metrics_http

        cert, key = tls_certs
        httpd = start_metrics_http(
            lambda: "ccs_test_metric 1\n", port=0,
            ssl_context=tenancy.server_ssl_context(cert, key))
        port = httpd.server_port
        try:
            # plaintext scrape: the handshake kills the connection
            with socket.create_connection(("127.0.0.1", port),
                                          timeout=5.0) as s:
                s.settimeout(5.0)
                s.sendall(b"GET /metrics HTTP/1.0\r\n\r\n")
                try:
                    assert b"200 OK" not in s.recv(4096)
                except OSError:
                    pass
            # TLS scrape works against the pinned CA
            ctx = tenancy.client_ssl_context(cert)
            with socket.create_connection(("127.0.0.1", port),
                                          timeout=5.0) as s:
                with ctx.wrap_socket(s, server_hostname="localhost") as w:
                    w.sendall(b"GET /metrics HTTP/1.0\r\n\r\n")
                    data = b""
                    while True:
                        chunk = w.recv(4096)
                        if not chunk:
                            break
                        data += chunk
            assert b"200 OK" in data and b"ccs_test_metric" in data
        finally:
            httpd.shutdown()


# ------------------------------------------------------------ router tier


def make_tenant_router(fakes, tenants, **cfg):
    defaults = dict(health_interval_s=0.05, health_timeout_s=0.2,
                    connect_timeout_s=2.0)
    defaults.update(cfg)
    router = CcsRouter([f"127.0.0.1:{f.port}" for f in fakes],
                       RouterConfig(**defaults),
                       tenants=tenants, link_token="tok-router").start()
    server = RouterServer(router, port=0, tenants=tenants).start()
    return router, server


class TestRouterTenancy:
    def test_link_token_and_tenant_forwarding(self):
        fake = FakeReplica()
        router, server = make_tenant_router([fake], router_directory())
        try:
            with CcsClient("127.0.0.1", server.port,
                           auth_token="tok-beta") as cli:
                reply = cli.submit_wire(ZMW)
                assert reply.reply(10.0)["type"] == "result"
            frame = fake.submits[0]
            # the replica link authenticates with the ROUTER's identity
            assert frame[protocol.FIELD_AUTH] == "tok-router"
            # ...and forwards the ORIGINAL submitter
            assert frame[protocol.FIELD_TENANT] == {"name": "beta"}
        finally:
            router.close()
            server.shutdown()
            fake.close()

    def test_spoofed_tenant_field_rewritten_at_edge(self):
        fake = FakeReplica()
        router, server = make_tenant_router([fake], router_directory())
        try:
            (r,) = wire_call(server.port,
                             [{"verb": "submit", "id": "s1", "zmw": ZMW,
                               "auth": "tok-beta",
                               "tenant": {"name": "gold"}}])
            assert r["type"] == "result"
            assert fake.submits[0][protocol.FIELD_TENANT] == \
                {"name": "beta"}
        finally:
            router.close()
            server.shutdown()
            fake.close()

    def test_unknown_forwarded_tenant_rejected(self):
        fake = FakeReplica()
        router, server = make_tenant_router([fake], router_directory())
        try:
            scope = MeasurementScope(default_registry())
            (r,) = wire_call(server.port,
                             [{"verb": "submit", "id": "s1", "zmw": ZMW,
                               "auth": "tok-router",
                               "tenant": {"name": "ghost"}}])
            assert r["code"] == protocol.ERR_UNAUTHORIZED
            assert "ghost" in r["error"]
            assert scope.counter_value("ccs_tenant_auth_failures_total",
                                       reason="unknown_tenant") == 1
            assert not fake.submits
        finally:
            router.close()
            server.shutdown()
            fake.close()

    def test_quota_queues_then_drains_fairly(self):
        fake = FakeReplica(mode="hold")
        router, server = make_tenant_router(
            [fake], router_directory(), fair_queue_depth=1,
            retry_after_ms=321.0)
        try:
            with CcsClient("127.0.0.1", server.port, timeout=10.0,
                           auth_token="tok-alpha") as cli:
                h1 = cli.submit_wire(ZMW)       # fills alpha's 1 slot
                assert wait_until(lambda: len(fake.submits) == 1)
                h2 = cli.submit_wire(ZMW)       # parks in the fair queue
                status = cli.status(10.0)
                ten = status[protocol.FIELD_TENANCY]
                rows = {r["name"]: r for r in
                        ten[protocol.KEY_TEN_TENANTS]}
                assert rows["alpha"]["inflight"] == 1
                assert rows["alpha"]["queued"] == 1
                assert ten[protocol.KEY_TEN_SHEDDING] is False
                # past the queue bound: structured overloaded + hint
                with pytest.raises(ServeError) as ei:
                    cli.submit_wire(ZMW).reply(10.0)
                assert ei.value.code == protocol.ERR_OVERLOADED
                assert ei.value.retry_after_ms == 321.0
                assert "over quota" in str(ei.value)
                # freeing the slot drains the parked request
                fake.release()
                assert h1.reply(10.0)["type"] == "result"
                assert wait_until(lambda: len(fake.submits) == 2)
                fake.release()
                assert h2.reply(10.0)["type"] == "result"
                rows = {r["name"]: r for r in cli.status(10.0)
                        [protocol.FIELD_TENANCY]
                        [protocol.KEY_TEN_TENANTS]}
                assert rows["alpha"]["completed"] == 2
                assert rows["alpha"]["rejected"] == 1
        finally:
            router.close()
            server.shutdown()
            fake.close()

    def test_burn_shedding_spares_priority_zero(self):
        fake = FakeReplica()
        router, server = make_tenant_router(
            [fake], router_directory(), shed_burn_threshold=0.5,
            retry_after_ms=250.0)
        try:
            # feed the meter a 90% violation window
            router._burn.observe("r", {"requests": 0, "violations": 0})
            router._burn.observe("r", {"requests": 10, "violations": 9})
            scope = MeasurementScope(default_registry())
            with CcsClient("127.0.0.1", server.port,
                           auth_token="tok-alpha") as cli:
                with pytest.raises(ServeError) as ei:
                    cli.submit_wire(ZMW).reply(10.0)
                assert ei.value.code == protocol.ERR_OVERLOADED
                assert ei.value.retry_after_ms == 250.0
                assert "shedding" in str(ei.value)
                ten = cli.status(10.0)[protocol.FIELD_TENANCY]
                assert ten[protocol.KEY_TEN_SHEDDING] is True
                assert ten[protocol.KEY_TEN_BURN] == pytest.approx(0.9)
            # priority 0 is never shed
            with CcsClient("127.0.0.1", server.port,
                           auth_token="tok-gold") as cli:
                assert cli.submit_wire(ZMW).reply(10.0)["type"] == "result"
            assert scope.counter_value("ccs_tenant_rejects_total",
                                       tenant="alpha", reason="shed") == 1
            assert router.status()["shed"] == 1
        finally:
            router.close()
            server.shutdown()
            fake.close()

    def test_shed_client_honors_retry_hint_no_hot_loop(self):
        """Regression: a shed request must PACE on the server's
        retry_after_ms, not hot-loop its retry budget instantly."""
        fake = FakeReplica()
        router, server = make_tenant_router(
            [fake], router_directory(), shed_burn_threshold=0.5,
            retry_after_ms=200.0)
        try:
            router._burn.observe("r", {"requests": 0, "violations": 0})
            router._burn.observe("r", {"requests": 10, "violations": 10})
            policy = RetryPolicy(max_attempts=3, base_delay_s=1e-4,
                                 max_delay_s=2.0, jitter=0.0)
            with CcsClient("127.0.0.1", server.port,
                           auth_token="tok-alpha") as cli:
                t0 = time.monotonic()
                with pytest.raises(RetriesExhausted):
                    cli.submit_with_retry(ZMW, policy=policy)
                elapsed = time.monotonic() - t0
            # without the hint the two backoffs total ~0.3ms; with it
            # they are 2 x 200ms
            assert elapsed >= 0.35
        finally:
            router.close()
            server.shutdown()
            fake.close()

    def test_close_flushes_parked_requests(self):
        fake = FakeReplica(mode="hold")
        router, server = make_tenant_router([fake], router_directory())
        try:
            with CcsClient("127.0.0.1", server.port, timeout=10.0,
                           auth_token="tok-alpha") as cli:
                cli.submit_wire(ZMW)
                assert wait_until(lambda: len(fake.submits) == 1)
                parked = cli.submit_wire(ZMW)
                router.close(drain=False)
                with pytest.raises(ServeError) as ei:
                    parked.reply(10.0)
                assert ei.value.code == protocol.ERR_CLOSED
        finally:
            router.close(drain=False)
            server.shutdown()
            fake.close()


# ----------------------------------------------------------- fleet wiring


class TestFleetWiring:
    def test_child_serve_args_pass_edge_flags_down(self):
        args = build_fleet_parser().parse_args(
            ["--tlsCert", "c.pem", "--tlsKey", "k.pem",
             "--authTokens", "t.json", "--serveArg=--maxBatch=8"])
        tail = child_serve_args(args)
        assert tail[tail.index("--tlsCert") + 1] == "c.pem"
        assert tail[tail.index("--tlsKey") + 1] == "k.pem"
        assert tail[tail.index("--authTokens") + 1] == "t.json"
        assert tail[-1] == "--maxBatch=8"   # user overrides come last

    def test_child_serve_args_stay_plain_without_flags(self):
        tail = child_serve_args(build_fleet_parser().parse_args([]))
        assert "--tlsCert" not in tail and "--authTokens" not in tail

    def test_fleet_verb_requires_auth(self):
        fake = FakeReplica()
        router, server = make_tenant_router([fake], router_directory())
        try:
            (r,) = wire_call(server.port,
                             [{"verb": protocol.VERB_FLEET, "id": "f1",
                               "action": "list"}])
            assert r["code"] == protocol.ERR_UNAUTHORIZED
            (r,) = wire_call(server.port,
                             [{"verb": protocol.VERB_FLEET, "id": "f2",
                               "action": "list", "auth": "tok-router"}])
            assert r["type"] == protocol.TYPE_FLEET
        finally:
            router.close()
            server.shutdown()
            fake.close()

    def test_health_probes_authenticate(self):
        """An authenticated replica stays healthy only when the router's
        link token is valid; a bad token benches it (probe errors are
        health strikes, not parse garbage)."""
        eng = stub_engine(max_batch=2, max_wait_ms=20.0, max_pending=16)
        eng.start()
        replica = CcsServer(eng, port=0, tenants=edge_directory())
        replica.start()
        good = CcsRouter([f"127.0.0.1:{replica.port}"],
                         RouterConfig(health_interval_s=0.05,
                                      health_timeout_s=0.5,
                                      connect_timeout_s=2.0),
                         link_token="tok-router").start()
        bad = CcsRouter([f"127.0.0.1:{replica.port}"],
                        RouterConfig(health_interval_s=0.05,
                                     health_timeout_s=0.5,
                                     connect_timeout_s=2.0),
                        link_token="tok-wrong").start()
        try:
            assert wait_until(
                lambda: good.status()["replicas"][0]["healthy"])
            assert wait_until(
                lambda: not bad.status()["replicas"][0]["healthy"])
        finally:
            good.close()
            bad.close()
            replica.shutdown()
            eng.close()


# ------------------------------------------- online token-map reload

def write_tokens(path, rows):
    path.write_text(json.dumps({"tenants": rows}))
    return str(path)


class TestReloadableDirectory:
    """ReloadableTenantDirectory: the --authTokens file followed online
    (SIGHUP or mtime change) without a rolling restart."""

    def make(self, tmp_path, rows, **kw):
        p = tmp_path / "tokens.json"
        write_tokens(p, rows)
        clock = [0.0]
        rd = tenancy.ReloadableTenantDirectory(
            str(p), clock=lambda: clock[0], **kw)
        return rd, p, clock

    def test_first_load_fails_loud(self, tmp_path):
        p = tmp_path / "tokens.json"
        p.write_text("{broken")
        with pytest.raises(ValueError):
            tenancy.ReloadableTenantDirectory(str(p))

    def test_mtime_reload_revokes_and_admits(self, tmp_path):
        rd, p, clock = self.make(
            tmp_path, [{"name": "a", "token": "ta"}])
        assert rd.authenticate("ta").name == "a"
        time.sleep(0.01)
        write_tokens(p, [{"name": "b", "token": "tb"}])
        # inside the recheck window the old map still answers
        assert rd.authenticate("ta") is not None
        clock[0] = 5.0
        assert rd.authenticate("ta") is None      # revoked
        assert rd.authenticate("tb").name == "b"  # admitted
        assert rd.get("b") is not None and rd.get("a") is None

    def test_malformed_reload_keeps_previous_map(self, tmp_path):
        scope = MeasurementScope(default_registry())
        rd, p, clock = self.make(
            tmp_path, [{"name": "a", "token": "ta"}])
        time.sleep(0.01)
        p.write_text("{broken")
        clock[0] = 5.0
        assert rd.authenticate("ta").name == "a"
        assert scope.counter_value("ccs_tenant_map_reloads_total",
                                   outcome="error") == 1
        # a later GOOD edit recovers
        time.sleep(0.01)
        write_tokens(p, [{"name": "a", "token": "ta2"}])
        clock[0] = 10.0
        assert rd.authenticate("ta") is None
        assert rd.authenticate("ta2").name == "a"

    def test_sighup_bypasses_recheck_window(self, tmp_path):
        import signal
        rd, p, clock = self.make(
            tmp_path, [{"name": "a", "token": "ta"}])
        prev = signal.getsignal(signal.SIGHUP)
        try:
            assert rd.install_sighup() is True
            time.sleep(0.01)
            write_tokens(p, [{"name": "b", "token": "tb"}])
            # clock never advances: only the signal can trigger reload
            assert rd.authenticate("tb") is None
            signal.raise_signal(signal.SIGHUP)
            assert rd.authenticate("tb").name == "b"
        finally:
            signal.signal(signal.SIGHUP, prev)

    def test_listener_and_fair_queue_refresh(self, tmp_path):
        rd, p, clock = self.make(
            tmp_path, [{"name": "a", "token": "ta", "max_inflight": 1}])
        fq = FairQueue(rd)
        rd.add_listener(fq.refresh)
        assert fq.try_admit("a", 1) == "dispatch"
        assert fq.try_admit("a", 2) == "queued"   # quota 1
        time.sleep(0.01)
        write_tokens(p, [
            {"name": "a", "token": "ta", "max_inflight": 4},
            {"name": "b", "token": "tb"}])
        clock[0] = 5.0
        rd.maybe_reload()
        # new tenant has admission state (no KeyError) and the existing
        # tenant adopted the raised quota without losing its counters
        assert fq.try_admit("b", 3) == "dispatch"
        assert fq.try_admit("a", 4) == "dispatch"
        rows = {r["name"]: r for r in fq.rows()}
        assert rows["a"]["max_inflight"] == 4
        assert rows["a"]["queued"] == 1           # parked item survives

    def test_token_revoked_mid_session(self, tmp_path):
        """Regression: revoking a token must reject the session's NEXT
        frame while the session itself (and its in-flight identity)
        survives the reload."""
        p = tmp_path / "tokens.json"
        write_tokens(p, [{"name": "alpha", "token": "tok-alpha"},
                         {"name": "beta", "token": "tok-beta"}])
        clock = [0.0]
        rd = tenancy.ReloadableTenantDirectory(
            str(p), clock=lambda: clock[0])
        eng = stub_engine(max_batch=2, max_wait_ms=20.0, max_pending=16)
        eng.start()
        srv = CcsServer(eng, port=0, tenants=rd)
        srv.start()
        try:
            with socket.create_connection(("127.0.0.1", srv.port),
                                          timeout=5.0) as s:
                s.settimeout(5.0)
                rf = s.makefile("rb")

                def call(frame):
                    s.sendall(protocol.encode_msg(frame))
                    return protocol.decode_line(rf.readline())

                r = call({"verb": "submit", "id": "s1", "zmw": ZMW,
                          "auth": "tok-alpha"})
                assert r["type"] == "result"
                time.sleep(0.01)
                write_tokens(p, [{"name": "beta", "token": "tok-beta"}])
                clock[0] = 5.0
                # same session, same token: the revocation bites on the
                # next frame...
                r = call({"verb": "submit", "id": "s2", "zmw": ZMW,
                          "auth": "tok-alpha"})
                assert r["type"] == "error"
                assert r["code"] == protocol.ERR_UNAUTHORIZED
                # ...but the session survives and a still-valid token
                # keeps working over it
                r = call({"verb": "submit", "id": "s3", "zmw": ZMW,
                          "auth": "tok-beta"})
                assert r["type"] == "result"
        finally:
            srv.shutdown()
            eng.close()


# --------------------------------------------- per-tenant SLO burn rate

class TestPerTenantShedRate:
    def burn_directory(self):
        return directory(
            Tenant("tolerant", "tok-tol", shed_burn_rate=0.95),
            Tenant("strict", "tok-str", shed_burn_rate=0.2),
            Tenant("alpha", "tok-alpha"),
            Tenant("_router", "tok-router", priority=0, trusted=True))

    def feed_burn(self, router, rate=0.9):
        router._burn.observe("r", {"requests": 0, "violations": 0})
        router._burn.observe("r", {"requests": 100,
                                   "violations": int(100 * rate)})

    def test_per_tenant_rate_overrides_fleet(self):
        fake = FakeReplica()
        router, server = make_tenant_router(
            [fake], self.burn_directory(), shed_burn_threshold=0.5)
        try:
            self.feed_burn(router, 0.9)
            # burn 0.9: the fleet default (0.5) sheds alpha, the strict
            # tenant's own 0.2 sheds it too, the tolerant tenant's 0.95
            # lets its work through
            with CcsClient("127.0.0.1", server.port,
                           auth_token="tok-tol") as cli:
                assert cli.submit_wire(ZMW).reply(10.0)["type"] == "result"
            for tok in ("tok-str", "tok-alpha"):
                with CcsClient("127.0.0.1", server.port,
                               auth_token=tok) as cli:
                    with pytest.raises(ServeError) as ei:
                        cli.submit_wire(ZMW).reply(10.0)
                    assert ei.value.code == protocol.ERR_OVERLOADED, tok
        finally:
            router.close()
            server.shutdown()
            fake.close()

    def test_per_tenant_rate_active_with_fleet_shedding_off(self):
        fake = FakeReplica()
        router, server = make_tenant_router(
            [fake], self.burn_directory())   # fleet threshold 0 = off
        try:
            self.feed_burn(router, 0.9)
            with CcsClient("127.0.0.1", server.port,
                           auth_token="tok-str") as cli:
                with pytest.raises(ServeError) as ei:
                    cli.submit_wire(ZMW).reply(10.0)
                assert ei.value.code == protocol.ERR_OVERLOADED
            # no per-tenant rate + fleet off = no shedding at all
            with CcsClient("127.0.0.1", server.port,
                           auth_token="tok-alpha") as cli:
                assert cli.submit_wire(ZMW).reply(10.0)["type"] == "result"
        finally:
            router.close()
            server.shutdown()
            fake.close()

    def test_token_file_round_trip(self, tmp_path):
        p = tmp_path / "tokens.json"
        write_tokens(p, [
            {"name": "a", "token": "ta", "shed_burn_rate": 0.5},
            {"name": "b", "token": "tb"}])
        d = TenantDirectory.from_file(str(p))
        assert d.get("a").shed_burn_rate == 0.5
        assert d.get("b").shed_burn_rate is None

    @pytest.mark.parametrize("bad", [-0.1, 1.5, "half", True])
    def test_bad_rates_rejected(self, tmp_path, bad):
        p = tmp_path / "tokens.json"
        write_tokens(p, [{"name": "a", "token": "ta",
                          "shed_burn_rate": bad}])
        with pytest.raises(ValueError, match="shed_burn_rate"):
            TenantDirectory.from_file(str(p))
