"""Multi-read mutation scorer tests: the central invariant (from reference
TestMultiReadMutationScorer.cpp) is Score(m) == (apply m, rescore) - baseline,
checked here for interior (extend+link) and edge (full refill) paths, on both
strands."""

import numpy as np
import pytest

from pbccs_tpu.models.arrow import mutations as M
from pbccs_tpu.models.arrow.params import ArrowConfig, BandingOptions, revcomp
from pbccs_tpu.models.arrow.scorer import ADD_SUCCESS, ArrowMultiReadScorer
from pbccs_tpu.simulate import simulate_zmw


def make_scorer(rng, tpl_len=40, n_passes=4, width=None):
    tpl, reads, strands, snr = simulate_zmw(rng, tpl_len, n_passes)
    width = width or (max(len(r) for r in reads) + 10)
    cfg = ArrowConfig(banding=BandingOptions(band_width=width))
    sc = ArrowMultiReadScorer(
        tpl, snr, reads, strands,
        tstarts=[0] * n_passes, tends=[tpl_len] * n_passes, config=cfg)
    return tpl, sc


def rescore_delta(sc, tpl, mut):
    """Ground truth: actually apply the mutation and rebuild a fresh scorer
    with remapped coordinates, then diff the total baseline."""
    mtp = M.target_to_query_positions([mut], len(tpl))
    new_tpl = M.apply_mutations(tpl, [mut])
    sc2 = ArrowMultiReadScorer(
        new_tpl, sc.snr,
        [sc._reads[i, : sc._rlens[i]] for i in range(sc.n_reads)],
        list(sc._strands[: sc.n_reads]),
        tstarts=[int(mtp[t]) for t in sc._tstarts[: sc.n_reads]],
        tends=[int(mtp[t]) for t in sc._tends[: sc.n_reads]],
        config=sc.config)
    return sc2.baseline_total() - sc.baseline_total()


@pytest.mark.parametrize("seed", range(3))
def test_interior_scores_match_rescore(seed, rng=None):
    rng = np.random.default_rng(400 + seed)
    tpl, sc = make_scorer(rng)
    assert all(s == ADD_SUCCESS for s in sc.statuses)
    L = len(tpl)
    muts = [M.substitution(L // 2, int((tpl[L // 2] + 1) % 4)),
            M.insertion(L // 2 + 2, int(rng.integers(0, 4))),
            M.deletion(L // 2 - 3),
            M.substitution(7, int((tpl[7] + 2) % 4)),
            M.deletion(L - 7)]
    scores = sc.score_mutations(muts)
    for mut, s in zip(muts, scores):
        truth = rescore_delta(sc, tpl, mut)
        assert abs(s - truth) < 5e-2 + 2e-3 * abs(truth), (mut, s, truth)


@pytest.mark.parametrize("seed", range(2))
def test_edge_scores_match_rescore(seed):
    rng = np.random.default_rng(500 + seed)
    tpl, sc = make_scorer(rng)
    L = len(tpl)
    muts = [M.substitution(0, int((tpl[0] + 1) % 4)),
            M.substitution(1, int((tpl[1] + 1) % 4)),
            M.deletion(2),
            M.substitution(L - 1, int((tpl[L - 1] + 1) % 4)),
            M.insertion(L, int(rng.integers(0, 4))),
            M.deletion(L - 1)]
    scores = sc.score_mutations(muts)
    for mut, s in zip(muts, scores):
        truth = rescore_delta(sc, tpl, mut)
        assert abs(s - truth) < 5e-2 + 2e-3 * abs(truth), (mut, s, truth)
    # Insertion at the very start of every read's window: the virtual score
    # penalizes the extra base, but a real application remaps windows to
    # exclude it (reference behavior: "untestable mutations, aka insertions
    # at ends", Consensus-inl.hpp:284).  Assert the faithful semantics:
    # unfavorable score, ~zero delta after application.
    (s_ins0,) = sc.score_mutations([M.insertion(0, int(rng.integers(0, 4)))])
    assert s_ins0 < 0
    truth = rescore_delta(sc, tpl, M.insertion(0, 0))
    assert abs(truth) < 5e-2


def test_true_template_beats_corruptions():
    """Scoring from a corrupted template: mutations restoring the truth must
    score positive, random others should not dominate."""
    rng = np.random.default_rng(600)
    tpl, reads, strands, snr = simulate_zmw(rng, 50, 8)
    width = max(len(r) for r in reads) + 10
    cfg = ArrowConfig(banding=BandingOptions(band_width=width))
    corrupted = tpl.copy()
    corrupted[25] = (corrupted[25] + 1) % 4
    sc = ArrowMultiReadScorer(corrupted, snr, reads, strands,
                              [0] * len(reads), [50] * len(reads), config=cfg)
    fix = M.substitution(25, int(tpl[25]))
    wrong = M.substitution(25, int((tpl[25] + 2) % 4))
    s_fix, s_wrong = sc.score_mutations([fix, wrong])
    assert s_fix > 0, s_fix
    assert s_fix > s_wrong


def test_apply_mutations_updates_template_and_scores():
    rng = np.random.default_rng(700)
    tpl, reads, strands, snr = simulate_zmw(rng, 50, 8)
    width = max(len(r) for r in reads) + 10
    cfg = ArrowConfig(banding=BandingOptions(band_width=width))
    corrupted = tpl.copy()
    corrupted[20] = (corrupted[20] + 1) % 4
    sc = ArrowMultiReadScorer(corrupted, snr, reads, strands,
                              [0] * len(reads), [50] * len(reads), config=cfg)
    base0 = sc.baseline_total()
    fix = M.substitution(20, int(tpl[20]))
    (gain,) = sc.score_mutations([fix])
    sc.apply_mutations([fix])
    base1 = sc.baseline_total()
    assert np.array_equal(sc.tpl, tpl)
    assert abs((base1 - base0) - gain) < 5e-2 + 2e-3 * abs(gain)
    assert base1 > base0
