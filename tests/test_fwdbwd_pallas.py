"""Pallas banded-fill kernel vs the pure-JAX reference path.

Pattern from the reference suite: the same scores must come out of every
kernel implementation (reference ConsensusCore TestRecursors.cpp:63-69 runs
one test body over scalar/SSE and dense/sparse recursors; here the pair is
JAX lax.scan vs the Pallas column-scan kernel, run in interpret mode on
CPU)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from pbccs_tpu.models.arrow.params import (
    snr_to_transition_table_host,
    template_transition_params,
)
from pbccs_tpu.ops import fwdbwd as fb
from pbccs_tpu.ops import fwdbwd_pallas as fp


def noisy_read(rng, tpl, sub=0.08, dele=0.06, ins=0.07):
    out = []
    for b in tpl:
        u = rng.random()
        if u < sub:
            out.append(int(rng.integers(0, 4)))
        elif u < sub + dele:
            continue
        else:
            out.append(int(b))
            if rng.random() < ins:
                out.append(int(rng.integers(0, 4)))
    return np.array(out, np.int8)


def _batch(rng, specs, Imax, Jmax, snr=8.0):
    """Build a padded read/template batch from (read_len_hint, tpl_len)."""
    R = len(specs)
    reads = np.full((R, Imax), 4, np.int8)
    rlens = np.zeros(R, np.int32)
    tpls = np.full((R, Jmax), 4, np.int8)
    tlens = np.zeros(R, np.int32)
    trans = np.zeros((R, Jmax, 4), np.float32)
    table = snr_to_transition_table_host(np.full(4, snr))
    for r, (_, J) in enumerate(specs):
        tpl = rng.integers(0, 4, J).astype(np.int8)
        read = noisy_read(rng, tpl)
        if len(read) == 0:
            read = np.array([0], np.int8)
        I = min(len(read), Imax)
        reads[r, :I] = read[:I]
        rlens[r] = I
        tpls[r, :J] = tpl
        tlens[r] = J
        padded = np.pad(tpl, (0, Jmax - J), constant_values=4)
        trans[r] = np.asarray(template_transition_params(
            jnp.asarray(padded), jnp.asarray(table, jnp.float32), jnp.int32(J)))
    return tuple(jnp.asarray(x) for x in (reads, rlens, tpls, trans, tlens))


WIDTH = 48


@pytest.fixture(scope="module")
def batch():
    rng = np.random.default_rng(20260730)
    specs = [(0, 2), (0, 1), (0, 5), (0, 90), (0, 64), (0, 80), (0, 33)]
    return _batch(rng, specs, Imax=160, Jmax=96)


def test_forward_matches_jax_path(batch):
    reads, rlens, tpls, trans, tlens = batch
    pa = fp.pallas_forward_batch(reads, rlens, tpls, trans, tlens, WIDTH)
    for r in range(reads.shape[0]):
        a = fb.banded_forward(reads[r], rlens[r], tpls[r], trans[r], tlens[r], WIDTH)
        np.testing.assert_allclose(np.asarray(pa.vals[r]), np.asarray(a.vals),
                                   atol=1e-5)
        np.testing.assert_array_equal(np.asarray(pa.offsets[r]),
                                      np.asarray(a.offsets))
        np.testing.assert_allclose(np.asarray(pa.log_scales[r]),
                                   np.asarray(a.log_scales), atol=1e-5)


def test_backward_matches_jax_path(batch):
    reads, rlens, tpls, trans, tlens = batch
    pb = fp.pallas_backward_batch(reads, rlens, tpls, trans, tlens, WIDTH)
    for r in range(reads.shape[0]):
        b = fb.banded_backward(reads[r], rlens[r], tpls[r], trans[r], tlens[r], WIDTH)
        np.testing.assert_allclose(np.asarray(pb.vals[r]), np.asarray(b.vals),
                                   atol=1e-5)
        np.testing.assert_allclose(np.asarray(pb.log_scales[r]),
                                   np.asarray(b.log_scales), atol=1e-5)


def test_logliks_match_and_mate(batch):
    """alpha/beta LLs agree with the JAX path and with each other (the
    reference's AlphaBetaMismatch mating check, SimpleRecursor.cpp:667-691)."""
    reads, rlens, tpls, trans, tlens = batch
    pa = fp.pallas_forward_batch(reads, rlens, tpls, trans, tlens, WIDTH)
    pb = fp.pallas_backward_batch(reads, rlens, tpls, trans, tlens, WIDTH)
    lla = np.asarray(fp.forward_loglik_batch(pa, rlens, tlens))
    llb = np.asarray(fp.backward_loglik_batch(pb, tlens))
    for r in range(reads.shape[0]):
        a = fb.banded_forward(reads[r], rlens[r], tpls[r], trans[r], tlens[r], WIDTH)
        ref = float(fb.forward_loglik(a, rlens[r], tlens[r]))
        assert abs(lla[r] - ref) < 2e-3, (r, lla[r], ref)
        assert abs(1.0 - lla[r] / llb[r]) < 1e-3, (r, lla[r], llb[r])


def test_fill_dispatch_forced_pallas(monkeypatch, batch):
    """fill_alpha_beta_batch with PBCCS_PALLAS=1 (interpret mode on CPU)
    agrees with the default JAX dispatch."""
    from pbccs_tpu.models.arrow.scorer import fill_alpha_beta_batch

    reads, rlens, tpls, trans, tlens = batch
    monkeypatch.delenv("PBCCS_PALLAS", raising=False)
    ref = fill_alpha_beta_batch(reads, rlens, tpls, trans, tlens, WIDTH)
    monkeypatch.setenv("PBCCS_PALLAS", "1")
    got = fill_alpha_beta_batch(reads, rlens, tpls, trans, tlens, WIDTH)
    for g, r in zip(jax.tree.leaves(got), jax.tree.leaves(ref)):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r), atol=2e-3)


def test_band_shift_clamp_drops_read_not_crashes():
    """A read/template length ratio beyond the kernel's max band shift must
    produce a (finite or -inf) score, never garbage; the scorer drops such
    reads via the mating gate."""
    rng = np.random.default_rng(7)
    tpl = rng.integers(0, 4, 16).astype(np.int8)
    read = np.concatenate([np.repeat(tpl, 12)])[:180].astype(np.int8)  # ~11x
    Imax, Jmax = 192, 96
    reads = np.full((1, Imax), 4, np.int8)
    reads[0, :len(read)] = read
    table = snr_to_transition_table_host(np.full(4, 8.0))
    padded = np.pad(tpl, (0, Jmax - len(tpl)), constant_values=4)
    trans = np.asarray(template_transition_params(
        jnp.asarray(padded), jnp.asarray(table, jnp.float32),
        jnp.int32(len(tpl))))[None]
    pa = fp.pallas_forward_batch(
        jnp.asarray(reads), jnp.asarray([len(read)], jnp.int32),
        jnp.asarray(padded[None]), jnp.asarray(trans),
        jnp.asarray([len(tpl)], jnp.int32), WIDTH)
    ll = np.asarray(fp.forward_loglik_batch(
        pa, jnp.asarray([len(read)], jnp.int32),
        jnp.asarray([len(tpl)], jnp.int32)))
    assert not np.isnan(ll).any()
    # the clamped band cannot represent this read: it must be deterministically
    # droppable (LL at the log-tiny floor), not silently mis-scored
    assert ll[0] < -60.0
