"""`ccs tune` tests: profiles, the resolution ladder, space, objective,
journal resume, and the search driver (subprocessless, via a
monkeypatched candidate runner).

The ladder contract under test (runtime/tuning.py):

    explicit flag / env  >  matching host profile  >  hand-tuned default

plus the degradation rules: fingerprint mismatch falls through with a
note, a corrupt/torn profile degrades without crashing, and nothing is
ever applied unless --tuneProfile / PBCCS_TUNE_PROFILE opted in.
"""

import dataclasses
import json
import os

import pytest

from pbccs_tpu.obs.metrics import default_registry
from pbccs_tpu.runtime import tuning
from pbccs_tpu.tune import driver, objective, space
from pbccs_tpu.tune.profile import (
    PROFILE_SCHEMA_VERSION,
    HostProfile,
    discover_profile,
    fingerprint_mismatch,
    host_fingerprint,
    load_profile,
    save_profile,
)

# ---------------------------------------------------------------- helpers


@pytest.fixture(autouse=True)
def clean_tuning_state(monkeypatch):
    """Every test starts and ends on hand-tuned defaults, with no
    ambient knob envs leaking in."""
    for var in ("PBCCS_BAND_W", "PBCCS_DENSE_CB", "PBCCS_TUNE_PROFILE",
                "PBCCS_TUNE_PROFILE_DIR"):
        monkeypatch.delenv(var, raising=False)
    tuning.reset()
    yield
    tuning.reset()


def make_profile(knobs, fingerprint=None):
    return HostProfile(fingerprint=fingerprint or host_fingerprint(),
                       knobs=knobs)


def write_profile(tmp_path, knobs, fingerprint=None, name="prof.json"):
    path = str(tmp_path / name)
    save_profile(make_profile(knobs, fingerprint), path)
    return path


class RecordingLog:
    def __init__(self):
        self.lines = []

    def notice(self, msg):
        self.lines.append(msg)

    info = warn = notice

    def text(self):
        return "\n".join(self.lines)


# ---------------------------------------------------------------- profiles


class TestHostProfile:
    def test_round_trip(self, tmp_path):
        path = write_profile(tmp_path, {"band_w": 48, "dense_cb": 2,
                                        "warmup_buckets": ["8x3x120"]})
        prof, note = load_profile(path)
        assert note is None
        assert prof.knobs == {"band_w": 48, "dense_cb": 2,
                              "warmup_buckets": ["8x3x120"]}
        assert prof.schema_version == PROFILE_SCHEMA_VERSION

    def test_profile_id_tracks_content(self):
        fp = {"platform": "cpu", "device_kind": "cpu",
              "device_count": 1, "jax_version": "1"}
        a = HostProfile(fingerprint=fp, knobs={"band_w": 48})
        b = HostProfile(fingerprint=fp, knobs={"band_w": 48})
        c = HostProfile(fingerprint=fp, knobs={"band_w": 96})
        assert a.profile_id == b.profile_id
        assert a.profile_id != c.profile_id
        assert len(a.profile_id) == 12

    def test_missing_file_degrades(self, tmp_path):
        prof, note = load_profile(str(tmp_path / "nope.json"))
        assert prof is None and "cannot read" in note

    @pytest.mark.parametrize("content", [
        "{torn",                                   # torn tail / not JSON
        "[]",                                      # alien shape
        json.dumps({"profile_schema_version": 99,
                    "fingerprint": {}, "knobs": {}}),   # future schema
        json.dumps({"profile_schema_version": 1,
                    "fingerprint": {"platform": "cpu"},
                    "knobs": {}}),                 # incomplete fingerprint
        json.dumps({"profile_schema_version": 1,
                    "fingerprint": {"platform": "cpu", "device_kind": "c",
                                    "device_count": 1, "jax_version": "1"},
                    "knobs": {"band_w": True}}),   # bool knob value
    ])
    def test_corrupt_profiles_degrade(self, tmp_path, content):
        p = tmp_path / "bad.json"
        p.write_text(content)
        prof, note = load_profile(str(p))
        assert prof is None
        assert note  # every degradation is explained

    def test_fingerprint_mismatch_names_field(self):
        host = host_fingerprint()
        other = dict(host, device_kind="TPU v5e")
        note = fingerprint_mismatch(other, host)
        assert "device_kind" in note
        assert fingerprint_mismatch(dict(host), host) is None

    def test_discover_picks_matching_skips_alien(self, tmp_path):
        host = host_fingerprint()
        write_profile(tmp_path, {"band_w": 48},
                      fingerprint=dict(host, jax_version="0.0.1"),
                      name="a-othergen.json")
        match = write_profile(tmp_path, {"band_w": 80}, name="b-this.json")
        # sorts before the match, so discovery must tolerate + explain it
        (tmp_path / "0-junk.json").write_text("{torn")
        prof, notes = discover_profile(str(tmp_path), host)
        assert prof is not None and prof.knobs["band_w"] == 80
        # the near-miss and the corrupt file are both explained
        assert any("jax_version" in n for n in notes)
        assert any("0-junk" in n for n in notes)
        assert os.path.exists(match)

    def test_discover_empty_dir(self, tmp_path):
        prof, notes = discover_profile(str(tmp_path), host_fingerprint())
        assert prof is None
        assert any("no profile" in n for n in notes)


# ------------------------------------------------------- resolution ladder


class TestResolutionLadder:
    def test_opt_in_only(self, tmp_path):
        """No spec, no env: nothing loads, knobs resolve to None."""
        assert tuning.configure(None) is False
        assert tuning.active_profile() is None
        assert tuning.knob_int("band_w") is None
        assert tuning.ledger_tag() == "none"
        for off in ("", "off", "none", "OFF"):
            assert tuning.configure(off) is False

    def test_profile_applies_and_attributes(self, tmp_path):
        path = write_profile(tmp_path, {"band_w": 48,
                                        "serve_max_wait_ms": 100.0})
        log = RecordingLog()
        assert tuning.configure(path, logger=log) is True
        prof = tuning.active_profile()
        assert tuning.knob_int("band_w") == 48
        assert tuning.knob_float("serve_max_wait_ms") == 100.0
        assert tuning.ledger_tag() == prof.profile_id
        assert "applied host profile" in log.text()
        # the applied gauge carries the profile id as a label
        text = default_registry().render_prometheus()
        assert "ccs_tune_profile_applied" in text
        assert prof.profile_id in text

    def test_env_spec_equivalent_to_flag(self, tmp_path, monkeypatch):
        path = write_profile(tmp_path, {"band_w": 80})
        monkeypatch.setenv("PBCCS_TUNE_PROFILE", path)
        assert tuning.configure(None) is True
        assert tuning.knob_int("band_w") == 80

    def test_band_w_flag_beats_profile_beats_default(self, tmp_path,
                                                     monkeypatch):
        from pbccs_tpu.models.arrow.params import (
            BandingOptions,
            effective_band_width,
        )

        # default schedule: 64 short, 96 long
        assert effective_band_width(BandingOptions(), 256) == 64
        # profile overrides the schedule default...
        tuning.configure(write_profile(tmp_path, {"band_w": 48}))
        assert effective_band_width(BandingOptions(), 256) == 48
        # ...env beats profile...
        monkeypatch.setenv("PBCCS_BAND_W", "72")
        assert effective_band_width(BandingOptions(), 256) == 72
        # ...explicit config beats everything
        assert effective_band_width(
            BandingOptions(band_width=128), 256) == 128

    def test_dense_cb_flag_beats_profile_beats_default(self, tmp_path,
                                                       monkeypatch):
        from pbccs_tpu.ops.dense_score_pallas import (
            _CB_DEFAULT,
            dense_cols_per_step,
        )

        assert dense_cols_per_step(64) == _CB_DEFAULT
        tuning.configure(write_profile(tmp_path, {"dense_cb": 2}))
        assert dense_cols_per_step(64) == 2
        monkeypatch.setenv("PBCCS_DENSE_CB", "8")
        assert dense_cols_per_step(64) == 8
        # the block-count clamp still applies to tuned values
        monkeypatch.delenv("PBCCS_DENSE_CB")
        assert dense_cols_per_step(1) == 1

    def test_serve_and_router_flags_default_to_ladder(self):
        """--maxBatch/--maxWaitMs/--routerSpillDepth parse to None so
        run_serve/run_router can resolve flag > profile > default."""
        from pbccs_tpu.serve.router import build_router_parser
        from pbccs_tpu.serve.server import build_serve_parser

        s = build_serve_parser().parse_args([])
        assert s.maxBatch is None and s.maxWaitMs is None
        assert s.tuneProfile is None
        r = build_router_parser().parse_args(["--replica", "h:1"])
        assert r.routerSpillDepth is None and r.tuneProfile is None

    def test_warmup_bucket_menu_from_profile(self, tmp_path):
        from pbccs_tpu.sched.warmup import build_parser

        args = build_parser().parse_args([])
        assert args.bucket is None   # optional when a profile supplies it
        tuning.configure(write_profile(
            tmp_path, {"warmup_buckets": ["8x3x120", "16x6x300"]}))
        assert tuning.knob_str_list("warmup_buckets") == \
            ["8x3x120", "16x6x300"]

    def test_fingerprint_mismatch_falls_through_with_note(self, tmp_path):
        host = host_fingerprint()
        path = write_profile(
            tmp_path, {"band_w": 48},
            fingerprint=dict(host, device_kind="TPU v5e"))
        log = RecordingLog()
        assert tuning.configure(path, logger=log) is False
        assert tuning.active_profile() is None
        assert "device_kind" in log.text()
        assert "hand-tuned defaults" in log.text()

    def test_corrupt_profile_degrades_without_crashing(self, tmp_path):
        p = tmp_path / "torn.json"
        p.write_text('{"profile_schema_version": 1, "knobs": {"ban')
        log = RecordingLog()
        assert tuning.configure(str(p), logger=log) is False
        assert tuning.knob_int("band_w") is None
        assert "not valid JSON" in log.text()

    def test_auto_discovery_scans_profile_dir(self, tmp_path,
                                              monkeypatch):
        write_profile(tmp_path, {"band_w": 48})
        monkeypatch.setenv("PBCCS_TUNE_PROFILE_DIR", str(tmp_path))
        assert tuning.configure("auto") is True
        assert tuning.knob_int("band_w") == 48

    def test_knob_type_guards(self, tmp_path):
        tuning.configure(write_profile(
            tmp_path, {"band_w": 48, "warmup_buckets": ["8x3x120"],
                       "label": "text"}))
        assert tuning.knob_int("warmup_buckets") is None
        assert tuning.knob_float("label") is None
        assert tuning.knob_str_list("band_w") is None


# ------------------------------------------------------------- knob space


class TestKnobSpace:
    def test_targets_cover_every_declared_knob(self):
        declared = {k.name for k in
                    (*space.BATCH_KNOBS, *space.SERVE_KNOBS)}
        declared.update(space.PROFILE_ONLY_KNOBS)
        assert declared == set(space.KNOB_TARGETS)

    def test_candidate_invocation_env_and_cli(self):
        argv, env = space.candidate_invocation(
            {"band_w": 48, "prepare_workers": 2})
        assert env == {"PBCCS_BAND_W": "48"}
        assert argv == ["--prepareWorkers", "2"]

    def test_candidate_invocation_rejects_profile_knobs(self):
        with pytest.raises(ValueError, match="not batch-sweepable"):
            space.candidate_invocation({"serve_max_batch": 8})
        with pytest.raises(ValueError, match="not batch-sweepable"):
            space.candidate_invocation({"mystery": 1})

    def test_affected_fields_union(self):
        assert space.affected_fields(
            {"band_w": 48, "mem_budget_bytes": 1 << 28}) == {
                "compiles", "compile_cache_hits", "compile_cache_misses",
                "budget_throttles"}
        assert space.affected_fields({"prepare_workers": 2}) == set()

    def test_batch_space_restrict_and_override(self):
        knobs = space.batch_space(["band_w"], {"band_w": (40, 56)})
        assert [k.name for k in knobs] == ["band_w"]
        assert knobs[0].candidates == (40, 56)
        # the master definition is untouched
        assert space.knob_by_name("band_w").candidates == (48, 64, 80, 96)


# -------------------------------------------------------------- objective


def meas(zps, wall=10.0, **kw):
    return objective.Measurement(zmws_per_sec=zps, wall_s=wall, **kw)


class TestObjective:
    def test_measure_medians(self):
        records = [
            {"kind": "batch_run", "zmws_per_sec": 10.0, "wall_s": 6.4,
             "padding_waste": 0.25, "peak_rss_bytes": 100},
            {"kind": "batch_run", "zmws_per_sec": 30.0, "wall_s": 2.1,
             "padding_waste": 0.25, "peak_rss_bytes": 300},
            {"kind": "batch_run", "zmws_per_sec": 20.0, "wall_s": 3.2,
             "padding_waste": 0.25, "peak_rss_bytes": 200},
        ]
        m = objective.measure(records)
        assert m.zmws_per_sec == 20.0 and m.wall_s == 3.2
        assert m.peak_rss_bytes == 200 and m.repeats == 3

    def test_measure_requires_throughput(self):
        assert objective.measure([{"kind": "batch_run"}]) is None
        assert objective.measure([]) is None

    def test_better_primary_and_ties(self):
        base = meas(100.0, padding_waste=0.2, peak_rss_bytes=100)
        assert objective.better(meas(110.0), base)          # clear win
        assert not objective.better(meas(90.0), base)       # clear loss
        # inside the tie band the tie-breakers decide
        tie_better = meas(101.0, padding_waste=0.1, peak_rss_bytes=100)
        tie_worse = meas(101.0, padding_waste=0.3, peak_rss_bytes=50)
        tie_equal = meas(100.0, padding_waste=0.2, peak_rss_bytes=100)
        assert objective.better(tie_better, base)
        assert not objective.better(tie_worse, base)
        assert not objective.better(tie_equal, base)  # incumbent keeps


# ---------------------------------------------------------------- journal


class TestJournal:
    def test_round_trip_and_resume(self, tmp_path):
        path = str(tmp_path / "journal.ndjson")
        j = driver.Journal(path, resume=False)
        res = driver.CandidateResult(
            {"band_w": 48}, ok=True, digest="d1",
            measurement=meas(10.0), records=[{"kind": "batch_run"}])
        j.put(res)
        j.put(driver.CandidateResult({"band_w": 96}, ok=False,
                                     reason="boom"))
        j2 = driver.Journal(path, resume=True)
        back = j2.get(driver.assignment_key({"band_w": 48}))
        assert back.ok and back.digest == "d1"
        assert back.measurement.zmws_per_sec == 10.0
        bad = j2.get(driver.assignment_key({"band_w": 96}))
        assert not bad.ok and bad.reason == "boom"

    def test_torn_tail_tolerated(self, tmp_path):
        path = str(tmp_path / "journal.ndjson")
        j = driver.Journal(path, resume=False)
        j.put(driver.CandidateResult({"band_w": 48}, ok=True,
                                     digest="d", measurement=meas(10.0)))
        with open(path, "a") as fh:
            fh.write('{"tune_journal": 1, "assignment": {"band_')
        j2 = driver.Journal(path, resume=True)
        assert j2.get(driver.assignment_key({"band_w": 48})) is not None

    def test_fresh_run_truncates(self, tmp_path):
        path = str(tmp_path / "journal.ndjson")
        j = driver.Journal(path, resume=False)
        j.put(driver.CandidateResult({}, ok=True, digest="d",
                                     measurement=meas(10.0)))
        j3 = driver.Journal(path, resume=False)   # no --resume: start over
        assert j3.get(driver.assignment_key({})) is None


# ------------------------------------------------------------ search driver


def batch_record(zps, *, compiles=3, dispatches=5, jax="j", wall=None):
    return {"kind": "batch_run", "schema_version": 1,
            "jax_version": jax, "platform": "cpu",
            "zmws_per_sec": zps, "wall_s": wall or round(64.0 / zps, 4),
            "polish_dispatches": dispatches, "compiles": compiles,
            "padding_waste": 0.1}


class FakeRunner:
    """Stands in for driver._run_candidate: a scripted candidate table
    keyed by assignment, counting invocations for resume assertions."""

    def __init__(self, table):
        self.table = table
        self.calls = []

    def __call__(self, cfg, assignment, calib):
        self.calls.append(dict(assignment))
        spec = self.table[driver.assignment_key(assignment)]
        if "reason" in spec:
            return driver.CandidateResult(assignment, ok=False,
                                          reason=spec["reason"])
        records = [batch_record(spec["zps"], **spec.get("rec", {}))
                   for _ in range(3)]
        return driver.CandidateResult(
            assignment, ok=True, digest=spec.get("digest", "base"),
            measurement=objective.measure(records), records=records)


def tune_cfg(tmp_path, knobs, **kw):
    cfg = driver.TuneConfig(
        workdir=str(tmp_path / "work"),
        out_path=str(tmp_path / "prof.json"),
        zmws=8, passes=3, tpl_len=120, chunk_size=8, repeat=3,
        knobs=knobs, **kw)
    os.makedirs(cfg.workdir, exist_ok=True)
    # the fake runner never reads the calibration file; skip synthesis
    open(os.path.join(cfg.workdir, "calibration.fasta"), "w").close()
    return cfg


@pytest.fixture
def one_knob():
    return [dataclasses.replace(space.knob_by_name("band_w"),
                                candidates=(48, 96))]


class TestRunSearch:
    def test_winner_ships_profile_loader_applies_it(self, tmp_path,
                                                    monkeypatch,
                                                    one_knob):
        runner = FakeRunner({
            driver.assignment_key({}): {"zps": 10.0},
            # band_w=48 is faster and only perturbs its declared
            # side-effect field (compile counts)
            driver.assignment_key({"band_w": 48}):
                {"zps": 14.0, "rec": {"compiles": 7}},
            driver.assignment_key({"band_w": 96}): {"zps": 9.0},
        })
        monkeypatch.setattr(driver, "_run_candidate", runner)
        summary = driver.run_search(tune_cfg(tmp_path, one_knob))
        assert summary["shipped"] is True
        assert summary["winner"]["assignment"] == {"band_w": 48}
        assert summary["winner"]["gain"] == pytest.approx(0.4)
        assert summary["referee"]["violations"] == []
        # the emitted profile round-trips through the loader
        assert tuning.configure(summary["profile"]) is True
        assert tuning.knob_int("band_w") == 48
        assert tuning.knob_str_list("warmup_buckets") == ["8x3x120"]
        assert tuning.ledger_tag() == summary["profile_id"]

    def test_output_change_rejected_not_ranked(self, tmp_path,
                                               monkeypatch, one_knob):
        runner = FakeRunner({
            driver.assignment_key({}): {"zps": 10.0},
            # faster but byte-different: MUST be rejected + reported
            driver.assignment_key({"band_w": 48}):
                {"zps": 50.0, "digest": "DIFFERENT"},
            driver.assignment_key({"band_w": 96}): {"zps": 9.0},
        })
        monkeypatch.setattr(driver, "_run_candidate", runner)
        summary = driver.run_search(tune_cfg(tmp_path, one_knob))
        assert summary["shipped"] is False
        reasons = [r["reason"] for r in summary["rejected"]]
        assert any("output differs" in r for r in reasons)
        assert not os.path.exists(str(tmp_path / "prof.json"))

    def test_referee_counter_drift_blocks_ship(self, tmp_path,
                                               monkeypatch, one_knob):
        runner = FakeRunner({
            driver.assignment_key({}): {"zps": 10.0},
            # same bytes, faster, but a NON-exempt counter drifted:
            # the perf_gate referee must veto the profile
            driver.assignment_key({"band_w": 48}):
                {"zps": 14.0, "rec": {"dispatches": 9}},
            driver.assignment_key({"band_w": 96}): {"zps": 9.0},
        })
        monkeypatch.setattr(driver, "_run_candidate", runner)
        summary = driver.run_search(tune_cfg(tmp_path, one_knob))
        assert summary["shipped"] is False
        bad = summary["referee"]["violations"]
        assert any(v["metric"] == "polish_dispatches" for v in bad)
        assert "NOT shipped" in summary["note"]

    def test_min_gain_gates_ship(self, tmp_path, monkeypatch, one_knob):
        runner = FakeRunner({
            driver.assignment_key({}): {"zps": 10.0},
            driver.assignment_key({"band_w": 48}): {"zps": 10.5},
            driver.assignment_key({"band_w": 96}): {"zps": 9.0},
        })
        monkeypatch.setattr(driver, "_run_candidate", runner)
        summary = driver.run_search(
            tune_cfg(tmp_path, one_knob, min_gain=0.10))
        assert summary["shipped"] is False
        assert "--minGain" in summary["note"]
        # smoke mode: negative min_gain force-ships a clean winner
        summary = driver.run_search(
            tune_cfg(tmp_path, one_knob, min_gain=-1.0))
        assert summary["shipped"] is True

    def test_no_winner_nothing_to_ship(self, tmp_path, monkeypatch,
                                       one_knob):
        runner = FakeRunner({
            driver.assignment_key({}): {"zps": 10.0},
            driver.assignment_key({"band_w": 48}): {"zps": 8.0},
            driver.assignment_key({"band_w": 96}): {"zps": 9.0},
        })
        monkeypatch.setattr(driver, "_run_candidate", runner)
        summary = driver.run_search(tune_cfg(tmp_path, one_knob))
        assert summary["shipped"] is False
        assert "nothing to ship" in summary["note"]

    def test_joint_refine_and_resume(self, tmp_path, monkeypatch):
        knobs = [
            dataclasses.replace(space.knob_by_name("band_w"),
                                candidates=(48,)),
            dataclasses.replace(space.knob_by_name("prepare_workers"),
                                candidates=(2,)),
        ]
        table = {
            driver.assignment_key({}): {"zps": 10.0},
            driver.assignment_key({"band_w": 48}): {"zps": 12.0},
            driver.assignment_key({"prepare_workers": 2}): {"zps": 11.0},
            driver.assignment_key({"band_w": 48, "prepare_workers": 2}):
                {"zps": 13.0},
        }
        runner = FakeRunner(table)
        monkeypatch.setattr(driver, "_run_candidate", runner)
        cfg = tune_cfg(tmp_path, knobs)
        summary = driver.run_search(cfg)
        assert summary["shipped"] is True
        assert summary["winner"]["assignment"] == \
            {"band_w": 48, "prepare_workers": 2}
        measured_once = len(runner.calls)
        # resume: every candidate comes back from the journal
        cfg2 = tune_cfg(tmp_path, knobs, resume=True)
        summary2 = driver.run_search(cfg2)
        assert summary2["winner"] == summary["winner"]
        assert len(runner.calls) == measured_once   # zero re-measures

    def test_defaults_run_failure_is_an_error(self, tmp_path,
                                              monkeypatch, one_knob):
        runner = FakeRunner({
            driver.assignment_key({}): {"reason": "exploded"}})
        monkeypatch.setattr(driver, "_run_candidate", runner)
        summary = driver.run_search(tune_cfg(tmp_path, one_knob))
        assert "error" in summary and "exploded" in summary["error"]


# -------------------------------------------------------------- perf_gate


class TestRefereeIgnore:
    def test_ignore_exempts_and_notes(self):
        pg = driver._load_perf_gate()
        base_records = [batch_record(10.0)]
        baseline = pg.build_baseline(base_records,
                                     select={"kind": "batch_run"})
        drifted = [batch_record(10.0, compiles=9)]
        violations, _ = pg.compare(baseline, drifted, counters_only=True)
        assert any(v["metric"] == "compiles" for v in violations)
        violations, notes = pg.compare(
            baseline, drifted, counters_only=True, ignore={"compiles"})
        assert violations == []
        assert any("exempted" in n for n in notes)

    def test_ignore_does_not_mask_other_drift(self):
        pg = driver._load_perf_gate()
        baseline = pg.build_baseline([batch_record(10.0)],
                                     select={"kind": "batch_run"})
        drifted = [batch_record(10.0, compiles=9, dispatches=8)]
        violations, _ = pg.compare(
            baseline, drifted, counters_only=True, ignore={"compiles"})
        assert any(v["metric"] == "polish_dispatches"
                   for v in violations)
