"""Typed concordance of the Quiver Pallas fills: Pallas kernel vs the JAX
banded recursor vs the dense log-space oracle -- the same cross-recursor
pattern the reference uses to pin its scalar vs SSE Quiver recursors
(reference ConsensusCore/src/Tests/TestRecursors.cpp:63-69).

The kernel runs in interpret mode on CPU (tests/conftest.py forces the CPU
backend); on TPU hardware the identical program compiles natively."""

import jax.numpy as jnp
import numpy as np
import pytest

from pbccs_tpu.models.quiver import ALL_MOVES, BASIC_MOVES
from pbccs_tpu.models.quiver.params import BandingOptions, QuiverConfig
from pbccs_tpu.models.quiver.pallas_fill import (pallas_quiver_backward_batch,
                                                 pallas_quiver_forward_batch,
                                                 quiver_loglik_batch)
from pbccs_tpu.models.quiver.recursor import (QuiverFeatureArrays,
                                              dense_loglik, feature_arrays,
                                              quiver_backward, quiver_forward,
                                              quiver_loglik,
                                              quiver_loglik_backward)

from test_quiver import _random_features


@pytest.fixture
def rng():
    return np.random.default_rng(20260731)


def _stack_feats(fas):
    return QuiverFeatureArrays(*(jnp.stack([getattr(f, n) for f in fas])
                                 for n in QuiverFeatureArrays._fields))


@pytest.mark.slow
@pytest.mark.parametrize("moves", [BASIC_MOVES, ALL_MOVES])
def test_pallas_fills_match_jax_and_oracle(rng, moves):
    """Batched Pallas alpha/beta fills agree with the JAX banded recursor
    (tight tolerance: same recurrence, different scan association) and
    with the dense oracle (banding tolerance), read for read."""
    W = 48
    cfg = QuiverConfig(moves_available=moves,
                       banding=BandingOptions(band_width=W))
    Imax, Jmax = 128, 64
    fas, tpls, tlens, rlens, refs = [], [], [], [], []
    for _ in range(6):
        J = int(rng.integers(8, 60))
        tpl = rng.integers(0, 4, J).astype(np.int8)
        feat = _random_features(rng, tpl)
        refs.append(dense_loglik(feat, tpl, cfg.qv_params,
                                 use_merge=bool(moves & 8)))
        fas.append(feature_arrays(feat, Imax))
        wpad = np.full(Jmax, 4, np.int8)
        wpad[:J] = tpl
        tpls.append(wpad)
        tlens.append(J)
        rlens.append(len(feat))

    feat_b = _stack_feats(fas)
    tpls_b = jnp.asarray(np.stack(tpls))
    rlens_b = jnp.asarray(rlens, jnp.int32)
    tlens_b = jnp.asarray(tlens, jnp.int32)

    alpha_b = pallas_quiver_forward_batch(feat_b, rlens_b, tpls_b, tlens_b,
                                          cfg, W)
    beta_b = pallas_quiver_backward_batch(feat_b, rlens_b, tpls_b, tlens_b,
                                          cfg, W)
    ll_a = np.asarray(quiver_loglik_batch(alpha_b, rlens_b, tlens_b))

    for r in range(len(fas)):
        a_jax = quiver_forward(fas[r], jnp.int32(rlens[r]),
                               jnp.asarray(tpls[r]), jnp.int32(tlens[r]),
                               cfg, W)
        b_jax = quiver_backward(fas[r], jnp.int32(rlens[r]),
                                jnp.asarray(tpls[r]), jnp.int32(tlens[r]),
                                cfg, W)
        lla_jax = float(quiver_loglik(a_jax, rlens[r], tlens[r]))
        llb_jax = float(quiver_loglik_backward(b_jax, tlens[r]))

        # cell-level concordance on the live columns
        J = tlens[r]
        np.testing.assert_allclose(
            np.asarray(alpha_b.vals[r, : J + 1]),
            np.asarray(a_jax.vals[: J + 1]), rtol=2e-4, atol=2e-5,
            err_msg=f"alpha cells read {r}")
        np.testing.assert_allclose(
            np.asarray(beta_b.vals[r, : J + 1]),
            np.asarray(b_jax.vals[: J + 1]), rtol=2e-4, atol=2e-5,
            err_msg=f"beta cells read {r}")

        # log-likelihood concordance: Pallas == JAX (tight) == oracle
        llb_pal = float(
            np.log(max(beta_b.vals[r, 0, 0], 1e-30))
            + np.where(np.arange(beta_b.log_scales.shape[1]) <= J,
                       np.asarray(beta_b.log_scales[r]), 0.0).sum())
        assert abs(ll_a[r] - lla_jax) < 1e-2, (r, ll_a[r], lla_jax)
        assert abs(llb_pal - llb_jax) < 1e-2, (r, llb_pal, llb_jax)
        assert abs(ll_a[r] - refs[r]) < 2e-2, (r, ll_a[r], refs[r])
        assert abs(llb_pal - refs[r]) < 2e-2, (r, llb_pal, refs[r])


def test_pallas_alpha_beta_mate(rng):
    """Forward and backward Pallas fills of the same pair agree on the
    total likelihood (the alpha/beta mating identity the scorers gate on)."""
    W = 48
    cfg = QuiverConfig(banding=BandingOptions(band_width=W))
    Imax, Jmax = 128, 64
    fas, tpls, tlens, rlens = [], [], [], []
    for _ in range(4):
        J = int(rng.integers(20, 60))
        tpl = rng.integers(0, 4, J).astype(np.int8)
        feat = _random_features(rng, tpl)
        fas.append(feature_arrays(feat, Imax))
        wpad = np.full(Jmax, 4, np.int8)
        wpad[:J] = tpl
        tpls.append(wpad)
        tlens.append(J)
        rlens.append(len(feat))
    feat_b = _stack_feats(fas)
    rlens_b = jnp.asarray(rlens, jnp.int32)
    tlens_b = jnp.asarray(tlens, jnp.int32)
    tpls_b = jnp.asarray(np.stack(tpls))
    alpha = pallas_quiver_forward_batch(feat_b, rlens_b, tpls_b, tlens_b,
                                        cfg, W)
    beta = pallas_quiver_backward_batch(feat_b, rlens_b, tpls_b, tlens_b,
                                        cfg, W)
    ll_a = np.asarray(quiver_loglik_batch(alpha, rlens_b, tlens_b))
    for r in range(4):
        J = tlens[r]
        ll_b = float(
            np.log(max(beta.vals[r, 0, 0], 1e-30))
            + np.where(np.arange(beta.log_scales.shape[1]) <= J,
                       np.asarray(beta.log_scales[r]), 0.0).sum())
        assert abs(ll_a[r] - ll_b) < 1e-2, (r, ll_a[r], ll_b)
