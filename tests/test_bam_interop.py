"""BAM interoperability beyond self-round-trip.

The round-1 risk: BamWriter/BamReader only ever validated against each
other, so a mirrored encoding bug (nibble order, tag typing, EOF block)
would pass every test yet produce files other tools reject.  Here:

  * a golden BAM is HAND-ASSEMBLED byte by byte from the SAM/BAM spec
    (sections 4.2-4.2.4) with Python's zlib for the BGZF deflate payload
    -- an implementation-independent encoding of the spec -- and
    BamReader must decode every field of it;
  * BamWriter output is re-validated at the byte level using Python's
    own zlib/gzip machinery (not this codebase's BGZF decoder): magic,
    sequence nibble order and odd-length padding, qual encoding, tag
    type codes, and the spec's exact 28-byte BGZF EOF terminator.
"""

import struct
import zlib

import pytest

from pbccs_tpu.io.bam import (BamHeader, BamReader, BamRecord, BamWriter,
                              ReadGroupInfo)

# SAM spec section 4.1.2: the special end-of-file marker (an empty BGZF
# block), byte for byte.
BGZF_EOF = bytes.fromhex(
    "1f8b08040000000000ff0600424302001b0003000000000000000000")

# BAM nibble code table, '=ACMGRSVTWYHKDBN' (spec 4.2.3)
NIB = {c: i for i, c in enumerate("=ACMGRSVTWYHKDBN")}


def bgzf_block(payload: bytes) -> bytes:
    """One BGZF block framing `payload`, built from the spec's gzip layout
    (fixed header with BC extra subfield carrying BSIZE)."""
    co = zlib.compressobj(6, zlib.DEFLATED, -15)
    cdata = co.compress(payload) + co.flush()
    bsize = 12 + 6 + len(cdata) + 8  # header+xlen + cdata + crc/isize
    out = bytearray()
    out += bytes.fromhex("1f8b08040000000000ff0600")  # gzip hdr, XLEN=6
    out += b"BC" + struct.pack("<HH", 2, bsize - 1)
    out += cdata
    out += struct.pack("<II", zlib.crc32(payload), len(payload))
    return bytes(out)


def golden_bam_bytes() -> bytes:
    """A complete one-record unaligned BAM written from the spec alone."""
    text = "@HD\tVN:1.5\tSO:unknown\n@RG\tID:grp1\tPL:PACBIO\n"
    hdr = b"BAM\x01" + struct.pack("<i", len(text)) + text.encode()
    hdr += struct.pack("<i", 0)  # n_ref = 0 (unaligned BAM)

    name = b"movie1/42/ccs\x00"
    seq = "ACGTN"                    # odd length: last nibble padded
    nib = bytearray()
    for i in range(0, len(seq) - 1, 2):
        nib.append((NIB[seq[i]] << 4) | NIB[seq[i + 1]])
    nib.append(NIB[seq[-1]] << 4)    # high nibble, low nibble zero
    qual = bytes([30, 31, 32, 33, 34])  # raw phred (not +33)

    tags = bytearray()
    tags += b"RGZgrp1\x00"                       # Z string
    tags += b"zmi" + struct.pack("<i", 42)       # int32
    tags += b"rqf" + struct.pack("<f", 0.999)    # float
    tags += b"snB" + b"f" + struct.pack("<i", 4) + struct.pack(
        "<4f", 5.0, 6.0, 7.0, 8.0)               # B float array

    rec = bytearray()
    rec += struct.pack("<iiBBHHHiiii", -1, -1, len(name), 255,
                       4680, 0, 4, len(seq), -1, -1, 0)
    # fields: refID=-1 pos=-1 l_read_name mapq bin n_cigar flag l_seq
    #         next_refID next_pos tlen
    rec += name + bytes(nib) + qual + bytes(tags)
    body = struct.pack("<i", len(rec)) + bytes(rec)

    return bgzf_block(hdr) + bgzf_block(body) + BGZF_EOF


def test_reader_decodes_spec_assembled_bam(tmp_path):
    path = tmp_path / "golden.bam"
    path.write_bytes(golden_bam_bytes())

    reader = BamReader(str(path))
    assert len(reader.header.read_groups) == 1  # @RG line decoded
    recs = list(reader)
    reader.close()
    assert len(recs) == 1
    r = recs[0]
    assert r.name == "movie1/42/ccs"
    assert r.seq == "ACGTN"
    assert r.qual == "".join(chr(q + 33) for q in [30, 31, 32, 33, 34])
    assert r.tags["RG"] == "grp1"
    assert r.tags["zm"] == 42
    assert r.tags["rq"] == pytest.approx(0.999, rel=1e-6)
    assert list(r.tags["sn"]) == [5.0, 6.0, 7.0, 8.0]


def _inflate_bgzf(data: bytes) -> bytes:
    """Decode a BGZF stream with zlib only (independent of io.bam)."""
    out, off = bytearray(), 0
    while off < len(data):
        assert data[off:off + 2] == b"\x1f\x8b", "not a gzip member"
        xlen = struct.unpack_from("<H", data, off + 10)[0]
        extra = data[off + 12: off + 12 + xlen]
        bsize = None
        i = 0
        while i + 4 <= len(extra):
            si1, si2, slen = extra[i], extra[i + 1], struct.unpack_from(
                "<H", extra, i + 2)[0]
            if (si1, si2, slen) == (ord("B"), ord("C"), 2):
                bsize = struct.unpack_from("<H", extra, i + 4)[0] + 1
            i += 4 + slen
        assert bsize is not None, "missing BC subfield"
        cstart = off + 12 + xlen
        cdata = data[cstart: off + bsize - 8]
        isize = struct.unpack_from("<I", data, off + bsize - 4)[0]
        payload = zlib.decompress(cdata, -15)
        assert len(payload) == isize
        assert zlib.crc32(payload) == struct.unpack_from(
            "<I", data, off + bsize - 8)[0]
        out += payload
        off += bsize
    return bytes(out)


def test_writer_output_validates_against_spec(tmp_path):
    path = tmp_path / "out.bam"
    header = BamHeader(read_groups=[ReadGroupInfo("movie1", "CCS")])
    w = BamWriter(str(path), header)
    w.write(BamRecord(name="movie1/7/ccs", seq="ACGTA",  # odd length
                      qual="".join(chr(q + 33) for q in [20, 21, 22, 23, 24]),
                      tags={"zm": 7, "rq": 0.5,
                            "sn": [4.0, 5.0, 6.0, 7.0]}))
    w.close()

    raw = path.read_bytes()
    assert raw.endswith(BGZF_EOF), "missing spec EOF block"

    payload = _inflate_bgzf(raw)
    assert payload[:4] == b"BAM\x01"
    l_text = struct.unpack_from("<i", payload, 4)[0]
    text = payload[8: 8 + l_text].decode()
    assert text.startswith("@HD")
    off = 8 + l_text
    n_ref = struct.unpack_from("<i", payload, off)[0]
    assert n_ref == 0
    off += 4

    block_size = struct.unpack_from("<i", payload, off)[0]
    rec = payload[off + 4: off + 4 + block_size]
    (ref_id, pos, l_name, mapq, _bin, n_cigar, flag, l_seq,
     nref2, npos2, tlen) = struct.unpack_from("<iiBBHHHiiii", rec, 0)
    assert (ref_id, pos) == (-1, -1)
    assert flag & 4            # unmapped
    assert n_cigar == 0
    assert l_seq == 5
    name = rec[32: 32 + l_name]
    assert name == b"movie1/7/ccs\x00"
    nib = rec[32 + l_name: 32 + l_name + (l_seq + 1) // 2]
    # 'ACGTA' -> (1,2),(4,8),(1,pad0); high nibble first
    assert list(nib) == [0x12, 0x48, 0x10]
    qual = rec[32 + l_name + 3: 32 + l_name + 3 + l_seq]
    assert list(qual) == [20, 21, 22, 23, 24]

    tagdata = bytes(rec[32 + l_name + 3 + l_seq:])
    assert b"zm" in tagdata and b"rq" in tagdata and b"sn" in tagdata
    zi = tagdata.index(b"zm")
    assert tagdata[zi + 2: zi + 3] in b"cCsSiI"   # integer-typed
    ri = tagdata.index(b"rq")
    assert tagdata[ri + 2: ri + 3] == b"f"
    si = tagdata.index(b"sn")
    assert tagdata[si + 2: si + 4] == b"Bf"       # float array
    n_arr = struct.unpack_from("<i", tagdata, si + 4)[0]
    assert n_arr == 4


def test_reader_writer_roundtrip_of_golden(tmp_path):
    """Write what the golden file contains; byte-decode both with zlib and
    compare the record payloads field by field."""
    gold = tmp_path / "gold.bam"
    gold.write_bytes(golden_bam_bytes())
    r = BamReader(str(gold))
    recs = list(r)
    r.close()

    out = tmp_path / "copy.bam"
    w = BamWriter(str(out), BamHeader.from_text(
        "@HD\tVN:1.5\tSO:unknown\n@RG\tID:grp1\tPL:PACBIO\n"))
    for rec in recs:
        w.write(rec)
    w.close()

    r2 = BamReader(str(out))
    recs2 = list(r2)
    r2.close()
    assert recs2[0].name == recs[0].name
    assert recs2[0].seq == recs[0].seq
    assert recs2[0].qual == recs[0].qual
    assert recs2[0].tags["zm"] == 42
    assert list(recs2[0].tags["sn"]) == [5.0, 6.0, 7.0, 8.0]
