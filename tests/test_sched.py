"""Device-fleet scheduler (pbccs_tpu/sched): routing, health, pipelining.

Runs on the conftest-forced 8-virtual-CPU-device platform, so the pool
tests exercise REAL multi-device dispatch (distinct jax.Device objects,
per-device executable caches) without hardware.  Polish-heavy parity
legs use tiny simulated ZMWs; pure scheduling legs use stub task fns.
"""

import threading
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from pbccs_tpu.obs.metrics import default_registry  # noqa: E402
from pbccs_tpu.pipeline import (  # noqa: E402
    Chunk,
    ConsensusSettings,
    Failure,
    Subread,
    process_chunks,
)
from pbccs_tpu.resilience import faults  # noqa: E402
from pbccs_tpu.sched import (  # noqa: E402
    DevicePool,
    DevicePoolConfig,
    PoolClosed,
    ScheduledPipeline,
)
from pbccs_tpu.simulate import simulate_zmw  # noqa: E402

reg = default_registry()


def make_pool(n=4, **cfg) -> DevicePool:
    return DevicePool(jax.devices()[:n], DevicePoolConfig(**cfg))


def worker_name(pool, i):
    return pool._workers[i].name


# ------------------------------------------------------------------ routing

def test_sticky_keeps_bucket_on_home_device():
    with make_pool(4) as pool:
        seen = []
        for _ in range(5):
            # sequential waits: the home is idle at every submit, so a
            # sticky bucket must stay put
            pool.submit("bucket-a", lambda d: seen.append(d) or d).result(30)
        assert len({d.id for d in seen}) == 1


def test_sticky_spreads_distinct_buckets():
    with make_pool(4) as pool:
        homes = {}
        for key in ("a", "b", "c", "d"):
            dev = pool.submit(key, lambda d: d).result(30)
            homes[key] = dev.id
        # the least-loaded tie-break prefers devices with fewer resident
        # buckets, so four idle devices take four distinct buckets
        assert len(set(homes.values())) == 4


def test_sticky_spills_when_home_busy():
    with make_pool(2) as pool:
        release = threading.Event()
        started = threading.Event()

        def slow(d):
            started.set()
            assert release.wait(30)
            return d

        f1 = pool.submit("k", slow)
        assert started.wait(30)
        # home busy and spill_depth=0: the second task must go elsewhere
        f2 = pool.submit("k", lambda d: d)
        d2 = f2.result(30)
        release.set()
        d1 = f1.result(30)
        assert d1.id != d2.id
        # the spill target became an additional home
        assert len(pool._sticky.homes("k")) == 2


def test_roundrobin_policy_cycles():
    with make_pool(3, policy="roundrobin") as pool:
        devs = [pool.submit("k", lambda d: d).result(30).id
                for _ in range(6)]
        assert devs[:3] == devs[3:]
        assert len(set(devs[:3])) == 3


def test_worker_index_pins(rng):
    with make_pool(4) as pool:
        for i in range(4):
            dev = pool.submit("k", lambda d: d, worker_index=i).result(30)
            assert dev.id == pool._workers[i].device.id


# ------------------------------------------------------------------- health

def test_device_failure_requeues_and_benches():
    scope = reg.scope()
    with make_pool(3, bench_after=2) as pool:
        bad = worker_name(pool, 0)
        with faults.active(f"sched.dispatch:error~{bad}"):
            futs = [pool.submit("k", lambda d: d, worker_index=0)
                    for _ in range(2)]
            # every task completes despite device 0 failing every attempt
            out = [f.result(60) for f in futs]
        assert all(d.id != pool._workers[0].device.id for d in out)
        assert pool._workers[0].benched
        st = pool.status()
        assert [d["benched"] for d in st["devices"]] == [True, False, False]
    assert scope.counter_value("ccs_sched_device_benched_total",
                               device=bad) == 1
    assert scope.counter_value("ccs_sched_requeues_total") >= 2
    assert scope.counter_value("ccs_sched_task_failures_total",
                               device=bad) >= 2


def test_benched_device_queue_drains_to_healthy():
    with make_pool(2, bench_after=1) as pool:
        bad = worker_name(pool, 0)
        release = threading.Event()
        started = threading.Event()

        def slow_ok(d):
            started.set()
            assert release.wait(30)
            return "ok"

        with faults.active(f"sched.dispatch:error~{bad}*1"):
            # park worker 1 so queued work stacks on worker 0
            f_slow = pool.submit("other", slow_ok, worker_index=1)
            assert started.wait(30)
            f1 = pool.submit("k", lambda d: "a", worker_index=0)  # fails once
            f2 = pool.submit("k", lambda d: "b", worker_index=0)  # stranded
            release.set()
            assert f_slow.result(30) == "ok"
            assert f1.result(60) == "a"
            assert f2.result(60) == "b"
        assert pool._workers[0].benched


def test_last_healthy_device_never_benched():
    with make_pool(1, bench_after=1) as pool:
        bad = worker_name(pool, 0)
        with faults.active(f"sched.dispatch:error~{bad}"):
            f = pool.submit("k", lambda d: d)
            exc = f.exception(30)
        assert exc is not None           # no other device to requeue to
        assert not pool._workers[0].benched
        # the pool still serves once the fault clears
        assert pool.submit("k", lambda d: "fine").result(30) == "fine"


def test_task_exception_propagates_when_all_devices_fail():
    with make_pool(3) as pool:
        def boom(d):
            raise ValueError("poison task")

        exc = pool.submit("k", boom).exception(60)
        assert isinstance(exc, ValueError)


def test_submit_after_close_raises():
    pool = make_pool(2)
    pool.close()
    with pytest.raises(PoolClosed):
        pool.submit("k", lambda d: d)


def test_close_without_wait_fails_queued_tasks():
    pool = make_pool(1)
    release = threading.Event()
    started = threading.Event()

    def slow(d):
        started.set()
        assert release.wait(30)
        return "done"

    f_running = pool.submit("k", slow)
    assert started.wait(30)
    f_queued = pool.submit("k", lambda d: "late")
    release.set()
    pool.close(wait=False)
    assert f_running.result(30) == "done"  # running tasks finish
    assert isinstance(f_queued.exception(30), PoolClosed) or \
        f_queued.result(0) == "late"  # raced the worker loop: either is fine


def test_watchdog_carries_thread_local_device():
    """An armed watchdog deadline moves the guarded callable to a fresh
    thread; it must carry the caller's thread-local jax.default_device
    (else every fleet polish with --polishTimeout lands on device 0)."""
    import jax.numpy as jnp

    from pbccs_tpu.resilience.watchdog import run_with_deadline

    target = jax.devices()[3]

    def placed_device():
        return next(iter(jnp.asarray([1.0]).devices()))

    with jax.default_device(target):
        assert run_with_deadline(placed_device, 30.0,
                                 site="test") == target
    # and with no override, behavior is unchanged
    assert run_with_deadline(placed_device, 30.0,
                             site="test") == jax.devices()[0]


def test_plain_exception_requeues_without_strike():
    """A non-device-shaped failure (poison input escaping quarantine)
    never benches healthy devices."""
    with make_pool(3, bench_after=1) as pool:
        def boom(d):
            raise ValueError("poison input")

        exc = pool.submit("k", boom).exception(60)
        assert isinstance(exc, ValueError)
        assert all(not w.benched for w in pool._workers)
        assert all(w.strikes == 0 for w in pool._workers)


def test_task_shaped_failure_retries_once_not_fleet_tour():
    """A deterministic task-shaped failure gets exactly ONE healthy-device
    retry before surfacing -- touring all N devices would cost N polish
    durations just to return the same error."""
    attempts = [0]
    with make_pool(4) as pool:
        def boom(d):
            attempts[0] += 1
            raise ValueError("deterministic bug")

        exc = pool.submit("k", boom).exception(60)
        assert isinstance(exc, ValueError)
    assert attempts[0] == 2


def test_pinned_task_fails_loudly_instead_of_requeueing():
    """A pin=True task that fails must surface its exception, not
    silently succeed on another device (a requeued warmup would leave
    the pinned device cold while reporting success).  Bare worker_index
    keeps initial-placement semantics: failures requeue normally."""
    ran_on = []
    with make_pool(3) as pool:
        def boom(d):
            ran_on.append(d)
            raise ValueError("pinned failure")

        exc = pool.submit("k", boom, worker_index=1, pin=True).exception(60)
        assert isinstance(exc, ValueError)
        assert len(ran_on) == 1 and ran_on[0].id == 1
        # unpinned placement on the same failing fn requeues off device 1
        ran_on.clear()
        exc = pool.submit("k2", boom, worker_index=1).exception(60)
        assert isinstance(exc, ValueError)
        assert len(ran_on) == 2          # one retry elsewhere, then surfaced
        assert ran_on[0].id == 1 and ran_on[1].id != 1


def test_submit_rejects_bad_placement():
    """worker_index must not wrap pythonically (an off-by-one pinning the
    LAST device would 'succeed' while the intended device stays cold) and
    pin=True without a target is a caller bug, not a no-op."""
    with make_pool(3) as pool:
        with pytest.raises(ValueError):
            pool.submit("k", lambda d: d, worker_index=-1)
        with pytest.raises(ValueError):
            pool.submit("k", lambda d: d, worker_index=3)
        with pytest.raises(ValueError):
            pool.submit("k", lambda d: d, pin=True)
        # in-range placement still works
        assert pool.submit("k", lambda d: d, worker_index=2).result(30).id == 2


def test_post_close_failure_completes_future():
    """A task that fails after close() gave up joining its worker must
    still complete its future (a post-close requeue would park it on a
    dead worker's deque and strand it forever)."""
    pool = make_pool(3)
    started, release = threading.Event(), threading.Event()

    def slow_fail(d):
        started.set()
        assert release.wait(30)
        raise RuntimeError("late failure")

    fut = pool.submit("k", slow_fail)
    assert started.wait(30)
    closer = threading.Thread(
        target=lambda: pool.close(join_timeout_s=0.1))
    closer.start()
    closer.join(30)            # close returns while the task still runs
    release.set()
    assert fut.wait(30), "future stranded after post-close failure"
    assert isinstance(fut.exception(), RuntimeError)


# -------------------------------------------------------- scheduled pipeline

def make_chunks(n, seed=20260803):
    rng = np.random.default_rng(seed)
    chunks = []
    for i in range(n):
        _, reads, _, snr = simulate_zmw(rng, 60, 5)
        chunks.append(Chunk(
            f"sched/{i}",
            [Subread(f"sched/{i}/{k}", r) for k, r in enumerate(reads)],
            snr))
    return chunks


def outputs(tally):
    return {r.id: (r.sequence, r.qualities) for r in tally.results}


@pytest.mark.slow
def test_scheduled_pipeline_matches_process_chunks():
    chunks = make_chunks(12)
    batches = [chunks[i: i + 4] for i in range(0, 12, 4)]
    settings = ConsensusSettings()

    want = {}
    want_counts = {f: 0 for f in Failure}
    for b in batches:
        t = process_chunks(list(b), settings)
        want.update(outputs(t))
        for f, c in t.counts.items():
            want_counts[f] += c

    with make_pool(4) as pool:
        pipe = ScheduledPipeline(pool, settings, prepare_workers=2)
        got, got_counts = {}, {f: 0 for f in Failure}
        order = []
        for idx, tally in pipe.run(
                (i, list(b), None) for i, b in enumerate(batches)):
            order.append(idx)
            got.update(outputs(tally))
            for f, c in tally.counts.items():
                got_counts[f] += c
        st = pool.status()
    assert order == [0, 1, 2]            # emission in submission order
    assert got == want                   # byte-identical to single-device
    assert got_counts == want_counts
    assert sum(d["tasks_done"] for d in st["devices"]) == 3


@pytest.mark.slow
def test_scheduled_pipeline_precomputed_and_chaos():
    """Journal-restored tallies pass through untouched, and a benched
    device mid-run loses zero ZMWs (the chaos acceptance leg in unit
    form; tools/sched_smoke.py runs the full-size version)."""
    chunks = make_chunks(8)
    batches = [chunks[:4], chunks[4:]]
    settings = ConsensusSettings()
    base = [process_chunks(list(b), settings) for b in batches]

    scope = reg.scope()
    with make_pool(3, bench_after=1) as pool:
        bad = worker_name(pool, 0)
        pipe = ScheduledPipeline(pool, settings, prepare_workers=1)
        with faults.active(f"sched.dispatch:error~{bad}"):
            items = [(0, None, base[0]),      # precomputed (restored)
                     (1, list(batches[1]), None)]
            emitted = dict(pipe.run(iter(items)))
    assert emitted[0] is base[0]
    assert outputs(emitted[1]) == outputs(base[1])
    assert emitted[1].total == base[1].total   # zero lost ZMWs
    assert scope.counter_value("ccs_sched_device_benched_total",
                               device=bad) == 1


def test_executor_first_attempt_device_failure_reaches_pool(monkeypatch):
    """A device-shaped polish failure on a fleet's FIRST attempt escapes
    the quarantine layer (raise_device_shaped=True) so the pool strikes
    the device and requeues the WHOLE batch; the requeued attempt runs
    with raise_device_shaped=False (local quarantine as usual)."""
    import types

    from pbccs_tpu import pipeline as pl
    from pbccs_tpu.pipeline import PreparedZmw, ResultTally

    FakeXla = type("XlaRuntimeError", (RuntimeError,), {})
    chunks = [Chunk(f"m/{i}", [Subread(f"m/{i}/0", np.zeros(8, np.int8))],
                    np.ones(4, np.float32)) for i in range(3)]

    def stub_prepare(cs, settings):
        read = types.SimpleNamespace(seq="ACGTACGT")
        return ResultTally(), [
            PreparedZmw(c, np.zeros(12, np.int8), [read], 0, 0, 0.0)
            for c in cs]

    flags = []

    def fake_polish(preps, settings, *, buckets=None, min_z=1,
                    on_error="bisect", raise_device_shaped=False,
                    prebaked=None):
        flags.append(raise_device_shaped)
        if len(flags) == 1:
            raise FakeXla("device fell over")
        return [(Failure.SUCCESS, None) for _ in preps]

    monkeypatch.setattr(pl, "prepare_batch", stub_prepare)
    monkeypatch.setattr(pl, "polish_prepared_batch", fake_polish)
    monkeypatch.setattr(pl, "_pinned_batch_shapes",
                        lambda preps, buckets, min_z: ((8, 8, 4), 4))

    scope = reg.scope()
    with make_pool(3) as pool:
        pipe = ScheduledPipeline(pool, ConsensusSettings(),
                                 prepare_workers=1)
        emitted = dict(pipe.run([(0, chunks, None)]))
        assert any(w.strikes == 1 for w in pool._workers)
    assert flags == [True, False]
    assert emitted[0].counts[Failure.SUCCESS] == 3   # zero lost ZMWs
    assert scope.counter_value("ccs_sched_requeues_total") == 1


# ------------------------------------------------------------- serve engine

def _stub_prep(chunk, settings):
    from pbccs_tpu.pipeline import PreparedZmw
    return None, PreparedZmw(chunk, np.zeros(12, np.int8), [], 0, 0, 0.0)


def _stub_polish_ok(preps, settings):
    return [(Failure.SUCCESS, None) for _ in preps]


def test_engine_pool_mode_completes_and_reports():
    from pbccs_tpu.serve.engine import CcsEngine, ServeConfig

    cfg = ServeConfig(max_batch=4, max_wait_ms=20.0, devices=4)
    with CcsEngine(config=cfg, prep_fn=_stub_prep,
                   polish_fn=_stub_polish_ok) as eng:
        chunks = make_chunks(10)
        reqs = [eng.submit(c) for c in chunks]
        for r in reqs:
            assert r.wait(60.0)
            assert r.error is None
        st = eng.status()
        assert st["sched"]["policy"] == "sticky"
        assert len(st["sched"]["devices"]) == 4
        assert sum(d["tasks_done"] for d in st["sched"]["devices"]) >= 1
    # pool is torn down with the engine
    assert eng._pool is None


def test_engine_pool_mode_survives_benched_device():
    from pbccs_tpu.serve.engine import CcsEngine, ServeConfig

    scope = reg.scope()
    cfg = ServeConfig(max_batch=2, max_wait_ms=20.0, devices=3)
    eng = CcsEngine(config=cfg, prep_fn=_stub_prep,
                    polish_fn=_stub_polish_ok)
    eng.start()
    try:
        bad = eng._pool._workers[0].name
        with faults.active(f"sched.dispatch:error~{bad}"):
            reqs = [eng.submit(c) for c in make_chunks(8)]
            for r in reqs:
                assert r.wait(60.0)
                # requeue to a healthy device: every request SUCCEEDS
                assert r.error is None, r.error
        assert scope.counter_value("ccs_sched_requeues_total") >= 1
        assert len(eng.status()["sched"]["devices"]) == 3
    finally:
        eng.close()


def test_engine_fleet_timeout_fails_after_two_devices_not_a_tour():
    """A polish that outlives the serve watchdog on TWO different devices
    is workload-shaped (e.g. a cold compile slower than the deadline):
    the batch must fail after the second expiry, not tour every device at
    one full timeout per hop while striking healthy hardware."""
    from pbccs_tpu.serve.engine import CcsEngine, ServeConfig

    attempts = []

    def slow_polish(preps, settings):
        attempts.append(1)
        time.sleep(1.0)
        return [(Failure.SUCCESS, None) for _ in preps]

    cfg = ServeConfig(max_batch=4, max_wait_ms=10.0, devices=4,
                      polish_timeout_ms=150.0)
    eng = CcsEngine(config=cfg, prep_fn=_stub_prep, polish_fn=slow_polish)
    eng.start()
    try:
        reqs = [eng.submit(c) for c in make_chunks(2)]
        for r in reqs:
            assert r.wait(60.0)
            assert r.error is not None
        assert len(attempts) == 2        # one requeue, then surfaced
        benched = [d for d in eng.status()["sched"]["devices"]
                   if d["benched"]]
        assert not benched               # no healthy device benched
    finally:
        eng.close()


def test_engine_single_device_default_unchanged():
    from pbccs_tpu.serve.engine import CcsEngine, ServeConfig

    with CcsEngine(config=ServeConfig(max_batch=2, max_wait_ms=20.0),
                   prep_fn=_stub_prep, polish_fn=_stub_polish_ok) as eng:
        reqs = [eng.submit(c) for c in make_chunks(4)]
        for r in reqs:
            assert r.wait(30.0)
        assert eng._pool is None
        assert "sched" not in eng.status()


# ------------------------------------------------------------------- warmup

def test_warmup_bucket_parsing():
    from pbccs_tpu.sched.warmup import parse_bucket

    assert parse_bucket("64x8x300") == (64, 8, 300)
    with pytest.raises(SystemExit):
        parse_bucket("64x8")
    with pytest.raises(SystemExit):
        parse_bucket("0x8x300")


@pytest.mark.slow
def test_warmup_runs_tiny_bucket(capsys):
    from pbccs_tpu.sched.warmup import run_warmup

    rc = run_warmup(["--bucket", "2x3x40", "--devices", "1"])
    assert rc == 0
    import json

    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["warmed"][0]["bucket"] == "2x3x40"
    assert out["warmed"][0]["shapes"]["Z"] >= 2


# ---------------------------------------------------------- CLI integration

@pytest.mark.slow
def test_cli_multi_device_output_byte_identical(tmp_path):
    """--devices 4 produces the identical FASTA output (and yield report)
    as the default single-device driver."""
    from pbccs_tpu import cli
    from pbccs_tpu.models.arrow.params import decode_bases

    rng = np.random.default_rng(20260803)
    fasta = tmp_path / "subreads.fasta"
    with open(fasta, "w") as f:
        for z in range(8):
            tpl, reads, _, _ = simulate_zmw(rng, 60, 5)
            start = 0
            for r in reads:
                seq = decode_bases(r)
                f.write(f">m/{z}/{start}_{start + len(seq)}\n{seq}\n")
                start += len(seq) + 20

    def run(devices):
        out = tmp_path / f"out_{devices}.fasta"
        rep = tmp_path / f"rep_{devices}.csv"
        rc = cli.run([str(out), str(fasta), "--skipChemistryCheck",
                      "--chunkSize", "3", "--reportFile", str(rep),
                      "--devices", str(devices)])
        assert rc == 0
        return out.read_bytes(), rep.read_bytes()

    single = run(1)
    multi = run(4)
    assert multi == single
