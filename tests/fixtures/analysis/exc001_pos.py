"""EXC001 positive: a bare except."""


def risky(fn):
    try:
        return fn()
    except:                        # noqa would not matter: bare is bare
        return None
