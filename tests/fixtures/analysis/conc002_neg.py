"""CONC002 negative: blocking work happens outside the lock; waiting on
the HELD condition (which releases it) is the one legal wait; str.join
and os.path.join are not thread joins."""
import os
import threading


class Collector:
    def __init__(self, work_queue):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self.queue = work_queue
        self.last = None
        self.ready = False

    def harvest(self, future, names):
        result = future.result()            # blocking, but no lock held
        item = self.queue.get()
        with self._lock:
            self.last = result
            label = ", ".join(names)        # str.join, not thread.join
            path = os.path.join("a", "b")   # os.path.join
        return item, label, path

    def await_ready(self):
        with self._cv:
            while not self.ready:
                self._cv.wait()             # waiting on the held condition
