"""Positive ATM001: a user-visible artifact published with a direct
write-mode open -- a crash or ENOSPC mid-write leaves a torn file
under the final path."""

import json


def publish_report(path, payload):
    with open(path, "w") as fh:      # ATM001 fires here
        json.dump(payload, fh)
