"""CONC001 negative: every cross-method write holds the lock (and the
Condition alias over the same lock counts as holding it)."""
import threading


class Tally:
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self.value = 0
        self.total = 0

    def bump(self):
        with self._lock:
            self.value += 1
            self.total += 1

    def reset(self):
        with self._cv:       # same lock, via the Condition alias
            self.value = 0
            self.total = 0
