"""JAX002 positive: host syncs on traced values inside jit."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def summarize(x):
    total = float(jnp.sum(x))      # float() concretizes the tracer
    host = np.asarray(x)           # device-to-host transfer
    first = x.sum().item()         # .item() is a sync
    return total, host, first
