"""EXC002 positive: a silent broad swallow with no stated reason."""


def best_effort(fn):
    try:
        fn()
    except Exception:
        pass
