"""ANA002 positive: this file does not parse (unbalanced paren)."""


def broken(:
    return 1
