"""JAX001 negative: branches on static args, shape metadata, and
identity checks are all static under trace."""
import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("flip",))
def step(x, flip, mask=None):
    if flip:                       # static_argnames -> static
        x = -x
    if mask is None:               # identity check on the tracer: static
        return x
    if x.ndim > 1:                 # shape metadata: static
        x = x.sum(axis=0)
    for _ in range(len(mask)):     # len() of a traced value: static
        x = jnp.where(mask, x, 0.0)
    return x
