"""The rule -> fixture map shared by tests/test_analysis.py and
tools/analyze_smoke.py (one source of truth, so the two gates cannot
drift).  Each AST rule has one minimal positive and one negative case;
rules without files here are covered by constructed-repo tests
(REG001-005 need a docs tree; ANA001 needs a baseline file)."""

# rule id -> (positive fixture, negative fixture)
AST_CASES = {
    "CONC001": ("conc001_pos.py", "conc001_neg.py"),
    "CONC002": ("conc002_pos.py", "conc002_neg.py"),
    "CONC003": ("conc003_pos.py", "conc003_neg.py"),
    "JAX001": ("jax001_pos.py", "jax001_neg.py"),
    "JAX002": ("jax002_pos.py", "jax002_neg.py"),
    "JAX003": ("jax003_pos.py", "jax003_neg.py"),
    "JAX004": ("jax004_pos.py", "jax004_neg.py"),
    "EXC001": ("exc001_pos.py", "exc001_neg.py"),
    "EXC002": ("exc002_pos.py", "exc002_neg.py"),
    "ATM001": ("atm001_pos.py", "atm001_neg.py"),
    "ATM002": ("atm002_pos.py", "atm002_neg.py"),
    "LSE001": ("lse001_pos.py", "lse001_neg.py"),
    "LSE002": ("lse002_pos.py", "lse002_neg.py"),
    "PRO002": ("pro002_pos.py", "pro002_neg.py"),
    "PRO003": ("pro003_pos.py", "pro003_neg.py"),
    "ANA002": ("ana002_pos.py", None),   # any parseable file is the neg
}

# Repo-wide rules whose fixtures need a constructed docs tree (the
# registry drift checks read DESIGN.md, which a path-scoped run cannot
# see).  tests/test_analysis.py copies each pair into a mini repo with
# the matching DESIGN.md table and asserts fire/quiet there.
REPO_CASES = {
    "REG010": ("reg010_pos.py", "reg010_neg.py"),
    "REG011": ("reg011_pos.py", "reg011_neg.py"),
    "REG012": ("reg012_pos.py", "reg012_neg.py"),
}
