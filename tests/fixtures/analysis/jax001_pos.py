"""JAX001 positive: Python control flow on traced values inside jit."""
import jax
import jax.numpy as jnp


@jax.jit
def clamp_positive(x):
    if x > 0:                      # traced param in a Python `if`
        return x
    return -x


@jax.jit
def iterate(x, tol):
    err = jnp.abs(x)
    while err > tol:               # traced value in a Python `while`
        x = x * 0.5
        err = jnp.abs(x)
    return x
