"""REG010 negative: every span name recorded here is listed in the
constructed mini repo's DESIGN.md span table (`reg010.documented`), and
non-obs `.span(...)` calls (a regex match object's span) never count as
trace sites."""

import re

from pbccs_tpu.obs import trace as obs_trace


def traced_work(tracer):
    with obs_trace.span("reg010.documented"):
        m = re.match(r"(a)+", "aaa")
        return m.span(1)        # regex span, not a trace site
