"""Negative PRO003: _locked helpers called under the owning lock, and
a _locked helper calling a sibling (the contract propagates)."""

import threading


class Router:
    def __init__(self):
        self._lock = threading.Lock()
        self._requests = {}

    def _complete_locked(self, rid):
        self._requests.pop(rid, None)

    def _sweep_locked(self):
        for rid in list(self._requests):
            self._complete_locked(rid)   # caller-is-_locked: fine

    def finish(self, rid):
        with self._lock:
            self._complete_locked(rid)
