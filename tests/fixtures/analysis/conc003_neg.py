"""CONC003 negative: lock acquisition order is consistent (always
Left._lock before Right._lock) -- the graph is acyclic."""
import threading


class Left:
    def __init__(self):
        self._lock = threading.Lock()
        self.right = Right()

    def poke(self):
        with self._lock:
            self.right.poke_back()   # Left._lock -> Right._lock only

    def poked(self):
        with self._lock:
            pass


class Right:
    def __init__(self):
        self._lock = threading.Lock()

    def poke_back(self):
        with self._lock:
            pass

    def tickle(self):
        with self._lock:
            pass                     # never calls back into Left


class DeferredLeft:
    """The would-be back edge lives in a nested def (a callback that
    runs later, in another execution context): no inline cycle."""

    def __init__(self):
        self._lock = threading.Lock()
        self.right = DeferredRight()

    def poke(self):
        with self._lock:
            self.right.enqueue()     # enqueue acquires nothing inline

    def poked(self):
        with self._lock:
            pass


class DeferredRight:
    def __init__(self):
        self._lock = threading.Lock()
        self.left = DeferredLeft()

    def enqueue(self):
        def later():                 # runs on another thread, later
            with self._lock:
                self.left.poked()

        return later
