"""Positive ATM002: temp-staged write renamed into place without an
fsync -- the rename can land while the data does not."""

import os


def publish(path, data):
    tmp = path + ".tmp"
    with open(tmp, "wb") as fh:      # ATM002: staged, never fsynced
        fh.write(data)
    os.replace(tmp, path)
