"""EXC001 negative: concrete exception types."""


def risky(fn):
    try:
        return fn()
    except (ValueError, OSError):
        return None
