"""CONC002 positive: blocking calls inside a with-lock body."""
import threading


class Collector:
    def __init__(self, work_queue):
        self._lock = threading.Lock()
        self.queue = work_queue
        self.last = None

    def harvest(self, future, worker):
        with self._lock:
            self.last = future.result()     # Future.result under lock
            item = self.queue.get()         # queue .get under lock
            worker.join()                   # thread join under lock
        return item
