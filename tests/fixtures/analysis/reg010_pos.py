"""REG010 positive: records a trace span whose name is missing from the
DESIGN.md span table (the constructed-repo test copies this file into a
mini repo whose table does NOT list `reg010.undocumented`)."""

from pbccs_tpu.obs import trace as obs_trace


def traced_work():
    with obs_trace.span("reg010.undocumented", detail=1):
        return 42
