"""Negative ATM002: the full tmp+fsync+rename idiom, stage and publish
split across methods of one class (the BamWriter shape)."""

import os


class Writer:
    def __init__(self, path):
        self.path = path
        self._tmp = path + ".tmp"
        self._fh = open(self._tmp, "wb")

    def write(self, data):
        self._fh.write(data)

    def close(self):
        self._fh.flush()
        os.fsync(self._fh.fileno())
        self._fh.close()
        os.replace(self._tmp, self.path)
