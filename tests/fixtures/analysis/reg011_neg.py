"""REG011 negative: every declared perf-ledger field matches the
constructed mini repo's DESIGN.md ledger-schema table (name AND class),
and a non-schema dict named something else never counts."""

LEDGER_FIELDS = {
    "reg011_documented": "meta",
    "reg011_shifty": "wall",
}

OTHER_FIELDS = {
    "not_a_ledger_field": "whatever",
}
