"""Negative LSE002: the finally releases on every path, exception
included."""


def charge(budget, batch, polish):
    lease = budget.admit(batch.nbytes)
    try:
        polish(batch)
    finally:
        lease.release()
