"""JAX003 positive: formatting traced values inside jit."""
import jax
import jax.numpy as jnp


@jax.jit
def noisy(x):
    total = jnp.sum(x)
    label = f"total={total}"       # f-string over a tracer
    name = str(total)              # str() over a tracer
    return x, label, name
