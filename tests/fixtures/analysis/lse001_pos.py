"""Positive LSE001: a host-budget lease acquired and then abandoned on
an early-return path (the ordered-emission deadlock bug class)."""


def prepare(budget, batch):
    lease = budget.admit(batch.nbytes)
    if batch.empty:
        return None              # LSE001: lease still held here
    lease.release()
    return batch
