"""EXC002 negative: the swallow states its reason (or narrows, or acts)."""


def best_effort(fn, log):
    try:
        fn()
    except Exception:  # noqa: BLE001 -- cleanup path must never raise
        pass


def best_effort_logged(fn, log):
    try:
        fn()
    except Exception as e:
        log.debug(f"ignored: {e!r}")


def best_effort_narrow(fn):
    try:
        fn()
    except OSError:
        pass
