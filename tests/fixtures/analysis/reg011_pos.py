"""REG011 positive: declares a perf-ledger field the constructed mini
repo's DESIGN.md ledger-schema table does not list (`reg011_alien`),
plus a field whose tolerance CLASS disagrees with the table
(`reg011_shifty` is `counter` here but `wall` in the table)."""

LEDGER_FIELDS = {
    "reg011_documented": "meta",
    "reg011_shifty": "counter",
    "reg011_alien": "ratio",
}
