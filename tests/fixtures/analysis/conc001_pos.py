"""CONC001 positive: `total` is written from two methods, unguarded."""
import threading


class Tally:
    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0
        self.total = 0

    def bump(self):
        with self._lock:
            self.value += 1
        self.total += 1      # write outside the lock

    def reset(self):
        with self._lock:
            self.value = 0
        self.total = 0       # second method, also outside the lock


class SplitLocks:
    """Every write holds A lock -- but not the SAME lock: no mutual
    exclusion exists between bump() and reset()."""

    def __init__(self):
        self._la = threading.Lock()
        self._lb = threading.Lock()
        self.shared = 0

    def bump(self):
        with self._la:
            self.shared += 1

    def reset(self):
        with self._lb:
            self.shared = 0
