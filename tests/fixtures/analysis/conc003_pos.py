"""CONC003 positive: Left takes its lock then calls into Right (which
takes Right's lock); Right does the reverse -- an AB/BA deadlock."""
import threading


class Left:
    def __init__(self):
        self._lock = threading.Lock()
        self.right = Right()

    def poke(self):
        with self._lock:
            self.right.poke_back()   # Left._lock -> Right._lock

    def poked(self):
        with self._lock:
            pass


class Right:
    def __init__(self):
        self._lock = threading.Lock()
        self.left = Left()

    def poke_back(self):
        with self._lock:
            pass

    def tickle(self):
        with self._lock:
            self.left.poked()        # Right._lock -> Left._lock
