"""Negative ATM001: the registered atomic helper owns the
tmp+fsync+rename discipline; read-mode opens never flag."""

import json

from pbccs_tpu.resilience.resources import atomic_output


def publish_report(path, payload):
    with atomic_output(path, "report") as fh:
        json.dump(payload, fh)


def load_report(path):
    with open(path) as fh:
        return json.load(fh)
