"""Positive PRO003: a completion helper with the _locked suffix called
without holding the owning lock -- completing a request the caller
does not own."""

import threading


class Router:
    def __init__(self):
        self._lock = threading.Lock()
        self._requests = {}

    def _complete_locked(self, rid):
        self._requests.pop(rid, None)

    def finish(self, rid):
        self._complete_locked(rid)       # PRO003: lock not held
