"""REG012 positive: declares a tunable knob the constructed mini
repo's DESIGN.md knobs table does not list (`reg012_alien`), plus a
knob whose TARGET disagrees with the table (`reg012_shifty` drives
`env:PBCCS_SHIFTY` here but `flag:--shifty` in the table)."""

KNOB_TARGETS = {
    "reg012_documented": "env:PBCCS_DOCUMENTED",
    "reg012_shifty": "env:PBCCS_SHIFTY",
    "reg012_alien": "flag:--alien",
}
