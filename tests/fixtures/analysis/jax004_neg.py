"""JAX004 negative: module-level jit, and memoized jit factories."""
import functools

import jax


def _body(v):
    return v + 1


apply = jax.jit(_body)             # module level: one cache, reused


@functools.lru_cache
def make_scaler(k):
    return jax.jit(lambda v: v * k)    # memoized factory: one per k


def setup(n):
    @functools.lru_cache
    def factory(k):
        # the memoized frame is NESTED inside a plain function: still
        # one wrapper per key, still exempt
        return jax.jit(lambda v: v + n + k)

    return factory
