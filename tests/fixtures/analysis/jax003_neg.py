"""JAX003 negative: formatting static metadata is fine."""
import jax


@jax.jit
def tagged(x):
    label = f"shape={x.shape} ndim={x.ndim}"    # static metadata
    return x, label
