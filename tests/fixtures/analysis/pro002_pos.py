"""Positive PRO002: the error path falls through into the success
reply, completing the request twice (exactly-once emission)."""


class Session:
    def send(self, msg):
        self.transport.write(msg)

    def _on_query(self, msg):
        if msg.get("bad"):
            self.send({"type": "error"})     # missing return
        self.send({"type": "result"})        # PRO002: double on bad path
