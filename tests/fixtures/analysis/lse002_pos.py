"""Positive LSE002: calls run while the lease is held, and no try in
the function releases it on an exception path."""


def charge(budget, batch, polish):
    lease = budget.admit(batch.nbytes)
    polish(batch)                # may raise: the lease would leak
    lease.release()
