"""JAX002 negative: conversions of static metadata are fine, and host
syncs OUTSIDE jit-reachable code are the normal way to read results."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def scale(x):
    n = float(len(x))              # len() of a tracer is static
    w = int(x.shape[0])            # shape metadata is static
    return x * (w / n)


def driver(x):                     # not jit-reachable: syncs are fine
    y = scale(x)
    return float(jnp.sum(y)), np.asarray(y)
