"""Negative LSE001/LSE002: every path releases -- directly, or by
transferring the lease to a releasing callback (the executor idiom)."""


def prepare(budget, batch, submit):
    lease = budget.admit(batch.nbytes)
    if lease is None:
        return None              # acquire aborted: nothing held
    if batch.empty:
        lease.release()
        return None

    def done(fut):
        lease.release()          # the callback owns the release now

    submit(batch, callback=done)
    return batch
