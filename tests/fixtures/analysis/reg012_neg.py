"""REG012 negative: every declared tunable knob matches the
constructed mini repo's DESIGN.md knobs table (name AND target), and a
non-inventory dict named something else never counts."""

KNOB_TARGETS = {
    "reg012_documented": "env:PBCCS_DOCUMENTED",
    "reg012_shifty": "flag:--shifty",
}

OTHER_TARGETS = {
    "not_a_knob": "whatever",
}
