"""Negative PRO002: exactly one completion per path -- a direct reply,
or a registered completion callback (the ownership-transfer rule)."""


class Session:
    def send(self, msg):
        self.transport.write(msg)

    def _on_query(self, msg):
        if msg.get("bad"):
            self.send({"type": "error"})
            return

        def on_done(result):
            self.send({"type": "result"})

        self.engine.submit(msg, callback=on_done)
