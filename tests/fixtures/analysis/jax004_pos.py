"""JAX004 positive: a fresh jit wrapper (empty compile cache) per call."""
import jax


def apply_scaled(x, k):
    f = jax.jit(lambda v: v * k)   # new jit object every apply_scaled call
    return f(x)


def apply_local(x):
    def body(v):
        return v + 1

    return jax.jit(body)(x)        # ditto, via a locally-defined function
