"""SDP-anchored POA banding tests.

Models the reference's banding validation intent (RangeFinder semantics,
reference ConsensusCore/src/C++/Poa/RangeFinder.cpp:72-167) plus the
properties the reference never tested because its snapshot computed ranges
without applying them: banded == unbanded decisions at fixture scale, and
draft cost scaling ~O(V * band) on long inserts.
"""

import os
import time

import numpy as np
import pytest

from pbccs_tpu.align.seeds import find_seeds
from pbccs_tpu.models.arrow.params import decode_bases
from pbccs_tpu.poa.banding import anchor_chain, sdp_vertex_ranges
from pbccs_tpu.simulate import simulate_zmw


def _draft(reads, band: bool):
    from pbccs_tpu.poa.sparse import SparsePoa

    os.environ["PBCCS_POA_BAND"] = "1" if band else "0"
    try:
        poa = SparsePoa()
        keys = [poa.orient_and_add_read(r) for r in reads]
        css, summaries = poa.find_consensus(3)
        return keys, css, summaries
    finally:
        os.environ.pop("PBCCS_POA_BAND", None)


def test_anchor_chain_monotone(rng):
    seeds = np.stack([rng.integers(0, 500, 200), rng.integers(0, 500, 200)],
                     axis=1).astype(np.int32)
    chain = anchor_chain(seeds)
    assert len(chain) >= 1
    assert (np.diff(chain[:, 0]) > 0).all()
    assert (np.diff(chain[:, 1]) > 0).all()


def test_anchor_chain_recovers_diagonal(rng):
    tpl = rng.integers(0, 4, 400).astype(np.int8)
    seeds = find_seeds(tpl, tpl, 6)
    chain = anchor_chain(seeds)
    # a self-comparison must chain (nearly) every on-diagonal seed
    diag = chain[chain[:, 0] == chain[:, 1]]
    assert len(diag) > 300


def test_banded_matches_unbanded_consensus(rng):
    """Band decisions == full-width decisions on model-scale ZMWs."""
    for trial in range(4):
        tpl, reads, strands, snr = simulate_zmw(rng, 400, 6)
        kb, cssb, sumb = _draft(reads, band=True)
        ku, cssu, sumu = _draft(reads, band=False)
        assert kb == ku
        assert decode_bases(cssb) == decode_bases(cssu)
        assert [s.extent_on_read for s in sumb] == \
            [s.extent_on_read for s in sumu]


def test_banding_python_matches_native(rng):
    """The Python fallback and the native engine take identical banded
    decisions (the native-vs-python identity the engines already guarantee
    unbanded must survive banding)."""
    from pbccs_tpu import native

    if native.native_poa() is None:
        pytest.skip("native library unavailable")
    tpl, reads, strands, snr = simulate_zmw(rng, 500, 6)
    os.environ.pop("PBCCS_NATIVE", None)
    kn, cssn, sumn = _draft(reads, band=True)
    os.environ["PBCCS_NATIVE"] = "0"
    try:
        kp, cssp, sump = _draft(reads, band=True)
    finally:
        os.environ.pop("PBCCS_NATIVE", None)
    assert kn == kp
    assert decode_bases(cssn) == decode_bases(cssp)
    assert [s.extent_on_consensus for s in sumn] == \
        [s.extent_on_consensus for s in sump]


def test_vertex_ranges_cover_anchors():
    """Every anchored consensus-path vertex's range covers its anchor
    +- WIDTH, and closure gives every vertex a nonempty range."""
    path = list(range(100))
    preds = [[v - 1] if v else [] for v in range(100)]
    succs = [[v + 1] if v < 99 else [] for v in range(100)]
    chain = np.array([[10, 12], [50, 55], [90, 93]], np.int32)
    ranges = sdp_vertex_ranges(100, path, preds, succs, path, chain, 120)
    assert ranges is not None
    assert (ranges[:, 1] > ranges[:, 0]).all()
    for css_pos, read_pos in chain:
        lo, hi = ranges[css_pos]
        assert lo <= max(read_pos - 30, 0)
        assert hi >= min(read_pos + 30, 120)
    # between anchors the closure interpolates: position 30 must allow
    # read rows near 32 +- (gap + width)
    lo, hi = ranges[30]
    assert lo <= 32 <= hi


def test_long_insert_draft_scales():
    """Draft cost per base stays ~flat with insert length (the property
    full-width POA lacks: 10kb would be ~17x the per-base cost of 600bp)."""
    from pbccs_tpu.poa.sparse import SparsePoa

    def per_base(tpl_len):
        # min over repeats: the 600bp denominator is a short run whose
        # single-shot timing is noise-prone on a loaded CI host
        best = np.inf
        for _ in range(3):
            rng = np.random.default_rng(11)
            tpl, reads, strands, snr = simulate_zmw(rng, tpl_len, 6)
            t0 = time.monotonic()
            poa = SparsePoa()
            for r in reads:
                poa.orient_and_add_read(r)
            css, _ = poa.find_consensus(2)
            dt = time.monotonic() - t0
            assert abs(len(css) - tpl_len) < tpl_len * 0.1
            best = min(best, dt / (tpl_len * len(reads)))
        return best

    short = per_base(600)
    long_ = per_base(8000)
    # measured ~1.3x on an idle host; 8x leaves headroom for CI noise while
    # still failing hard if the fill regresses to O(V * I) (~13x+)
    assert long_ < 8 * short, (short, long_)


def test_orientation_still_detected_banded(rng):
    """Reverse-strand passes commit with rc=True under banding."""
    from pbccs_tpu.poa.sparse import SparsePoa

    tpl, reads, strands, snr = simulate_zmw(rng, 700, 6)
    poa = SparsePoa()
    for r in reads:
        assert poa.orient_and_add_read(r) >= 0
    assert poa.reverse_complemented == [bool(s) for s in strands]
    css, summaries = poa.find_consensus(2)
    assert abs(len(css) - len(tpl)) < 0.1 * len(tpl)
    # every pass aligned over (nearly) the full consensus
    for s in summaries:
        lo, hi = s.extent_on_consensus
        assert hi - lo > 0.8 * len(css)


# ---------------------------------------------------------------------------
# guided (argmax-path) recursor rebanding -- fwdbwd.guided_band_offsets,
# the TPU analogue of the reference's guide-matrix rebanding + flip-flop
# (reference ConsensusCore/src/C++/Arrow/SimpleRecursor.cpp:642-757)
# ---------------------------------------------------------------------------


def _drifted_fill_case(rng=None, L=2500, W=16):
    """A template/read pair whose alignment path drifts past W/2 rows off
    the straight diagonal (small W stands in for 15 kb at CPU test cost;
    pinned draw: seed 0 / L=2500 / read 0 drifts ~2x the band half-width)."""
    from pbccs_tpu.simulate import make_transition_track

    rng = rng or np.random.default_rng(0)
    tpl, reads, strands, snr = simulate_zmw(rng, L, 2)
    rd = reads[0] if strands[0] == 0 else reads[1]
    trans = make_transition_track(tpl, snr).astype(np.float32)
    I, J = len(rd), len(tpl)
    rpad = np.full(I + 8, 4, np.int8); rpad[:I] = rd
    tpad = np.full(J + 2, 4, np.int8); tpad[:J] = tpl
    trpad = np.zeros((J + 2, 4), np.float32); trpad[:J] = trans
    return rpad, I, tpad, trpad, J, W


def test_guided_offsets_invariants():
    import jax.numpy as jnp

    from pbccs_tpu.ops.fwdbwd import (MAX_BAND_ADVANCE, banded_forward,
                                      guided_band_offsets)

    rpad, I, tpad, trpad, J, W = _drifted_fill_case()
    alpha = banded_forward(jnp.asarray(rpad), jnp.int32(I),
                           jnp.asarray(tpad), jnp.asarray(trpad),
                           jnp.int32(J), W)
    off = np.asarray(guided_band_offsets(alpha.vals, alpha.offsets,
                                         jnp.int32(I), jnp.int32(J), W))
    d = np.diff(off)
    assert (d >= 0).all(), "offsets must be monotone"
    assert (d <= MAX_BAND_ADVANCE).all(), "band advance capped"
    assert off[0] == 0 and off[1] <= 1, "pinned-start rows stay in band"
    assert off[J] <= I <= off[J] + W - 1, "pinned corner stays in band"


def test_guided_refill_recovers_clipped_likelihood():
    """With W/2 below the path drift the diagonal band clips probability
    mass while alpha/beta stay consistent (same band, so the mating gate
    cannot see it); guided refills must recover strictly more likelihood
    (keep-better: never less) and keep the fills mated -- the round-4
    15 kb accuracy failure mode."""
    import jax.numpy as jnp

    from pbccs_tpu.models.arrow.scorer import fill_alpha_beta_batch

    rpad, I, tpad, trpad, J, W = _drifted_fill_case()
    args = (jnp.asarray(rpad)[None], jnp.asarray([I], jnp.int32),
            jnp.asarray(tpad)[None], jnp.asarray(trpad)[None],
            jnp.asarray([J], jnp.int32))
    _, _, la0, lb0, _, _ = fill_alpha_beta_batch(*args, W, False,
                                                 guided_passes=0)
    _, _, la2, lb2, _, _ = fill_alpha_beta_batch(*args, W, False,
                                                 guided_passes=2)
    la0, lb0 = float(la0[0]), float(lb0[0])
    la2, lb2 = float(la2[0]), float(lb2[0])
    assert abs(1.0 - la2 / lb2) <= 1e-3, "guided fills must mate"
    assert la2 > la0 + 30.0, \
        f"guided refill should recover clipped mass ({la0=} {la2=})"


@pytest.mark.parametrize("guided", [1, 2])
def test_guided_pallas_matches_jax(guided):
    """Pallas (interpret) and pure-JAX guided fills agree on LLs."""
    import jax.numpy as jnp

    from pbccs_tpu.models.arrow.scorer import fill_alpha_beta_batch

    rpad, I, tpad, trpad, J, W = _drifted_fill_case(L=300, W=16)
    args = (jnp.asarray(rpad)[None], jnp.asarray([I], jnp.int32),
            jnp.asarray(tpad)[None], jnp.asarray(trpad)[None],
            jnp.asarray([J], jnp.int32))
    aj, bj, laj, lbj, _, _ = fill_alpha_beta_batch(*args, W, False,
                                                   guided_passes=guided)
    ap, bp, lap, lbp, _, _ = fill_alpha_beta_batch(*args, W, True,
                                                   guided_passes=guided)
    np.testing.assert_array_equal(np.asarray(aj.offsets),
                                  np.asarray(ap.offsets)[:, : J + 3])
    np.testing.assert_allclose(float(laj[0]), float(lap[0]),
                               rtol=0, atol=2e-3)
    np.testing.assert_allclose(float(lbj[0]), float(lbp[0]),
                               rtol=0, atol=2e-3)
