"""Off-model noise robustness (round-1 weak item: all accuracy claims
rested on reads sampled from the model itself).

Two model-mismatched read corruptions the Arrow HMM does not generate:
bursty error clusters (local stretches of garbage, e.g. polymerase
stalls) and systematic homopolymer lengthening (a real PacBio bias).
The refinement must not diverge on such input: the pipeline completes,
tallies are sane, and the consensus stays near the truth -- degraded
gracefully, not catastrophically.
"""

import numpy as np
import pytest

from pbccs_tpu.align.pairwise import align as nw_align
from pbccs_tpu.models.arrow.params import decode_bases, revcomp
from pbccs_tpu.pipeline import Chunk, Failure, Subread, process_chunks
from pbccs_tpu.simulate import simulate_zmw


def _aligned_accuracy(seq: str, truth_codes: np.ndarray) -> float:
    fwd = nw_align(seq, decode_bases(truth_codes)).accuracy
    rev = nw_align(seq, decode_bases(revcomp(truth_codes))).accuracy
    return max(fwd, rev)


def _add_bursts(rng, read: np.ndarray, n_bursts: int = 2) -> np.ndarray:
    """Replace n short windows with random garbage and insert a few extra
    bases -- error clusters no HMM pass structure explains."""
    out = read.copy()
    for _ in range(n_bursts):
        if len(out) < 20:
            break
        pos = int(rng.integers(5, len(out) - 10))
        blen = int(rng.integers(3, 7))
        out[pos: pos + blen] = rng.integers(0, 4, blen)
        ins = rng.integers(0, 4, int(rng.integers(1, 4))).astype(np.int8)
        out = np.concatenate([out[:pos], ins, out[pos:]])
    return out


def _lengthen_homopolymers(rng, read: np.ndarray, p: float = 0.3) -> np.ndarray:
    """Duplicate a base after each homopolymer run with probability p."""
    parts = []
    i = 0
    while i < len(read):
        j = i
        while j < len(read) and read[j] == read[i]:
            j += 1
        parts.append(read[i:j])
        if j - i >= 2 and rng.random() < p:
            parts.append(read[i:i + 1])
        i = j
    return np.concatenate(parts)


@pytest.mark.slow
def test_bursty_reads_converge_gracefully(rng):
    chunks, truths = [], []
    for z in range(3):
        tpl, reads, strands, snr = simulate_zmw(rng, 250, 8)
        noisy = [_add_bursts(rng, r) for r in reads]
        chunks.append(Chunk(f"burst/{z}",
                            [Subread(f"burst/{z}/{i}", r)
                             for i, r in enumerate(noisy)], snr))
        truths.append(tpl)
    tally = process_chunks(chunks)
    assert sum(tally.counts.values()) == 3     # every ZMW tallied once
    assert tally.counts[Failure.SUCCESS] >= 2  # bursts must not sink yield
    for res in tally.results:
        z = int(res.id.split("/")[1])
        acc = _aligned_accuracy(res.sequence, truths[z])
        # bursts land at independent positions per read, so consensus
        # stays near truth; catastrophic divergence would crater this
        assert acc > 0.95, (res.id, acc)
        assert 0.5 < res.predicted_accuracy <= 1.0


@pytest.mark.slow
def test_homopolymer_bias_degrades_gracefully(rng):
    chunks, truths = [], []
    for z in range(3):
        tpl, reads, strands, snr = simulate_zmw(rng, 250, 8)
        noisy = [_lengthen_homopolymers(rng, r) for r in reads]
        chunks.append(Chunk(f"hp/{z}",
                            [Subread(f"hp/{z}/{i}", r)
                             for i, r in enumerate(noisy)], snr))
        truths.append(tpl)
    tally = process_chunks(chunks)
    assert sum(tally.counts.values()) == 3
    assert tally.counts[Failure.SUCCESS] >= 2  # bias must not sink yield
    # a systematic bias shared by every read CAN shift consensus bases at
    # biased sites (the reference would too); the requirement is graceful
    # degradation with the predicted accuracy honest about the damage
    for res in tally.results:
        z = int(res.id.split("/")[1])
        acc = _aligned_accuracy(res.sequence, truths[z])
        assert acc > 0.9, (res.id, acc)
        # prediction must not be wildly overconfident versus realized
        assert res.predicted_accuracy - acc < 0.1, (
            res.id, res.predicted_accuracy, acc)
