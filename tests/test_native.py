"""Native C++ library vs the pure-Python reference implementations
(BGZF codec round trips + SDP chaining equivalence)."""

import io

import numpy as np
import pytest

from pbccs_tpu import native
from pbccs_tpu.align import seeds as seedlib
from pbccs_tpu.io.bam import BgzfReader, BgzfWriter, _BGZF_EOF

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native library unavailable")


def test_bgzf_native_compress_python_read(rng):
    payload = rng.integers(0, 256, 300_000, dtype=np.uint8).tobytes()
    packed = native.bgzf_compress(payload)
    buf = io.BytesIO(packed + _BGZF_EOF)
    rd = BgzfReader(buf)
    assert rd.read(len(payload) + 10) == payload


def test_bgzf_python_write_native_decompress(rng):
    payload = rng.integers(0, 256, 200_000, dtype=np.uint8).tobytes()
    buf = io.BytesIO()
    w = BgzfWriter(buf)
    w.write(payload)
    w.close()
    got = native.bgzf_decompress(buf.getvalue(), expected_size=len(payload) + 64)
    assert got == payload


def test_bgzf_native_roundtrip_empty():
    assert native.bgzf_compress(b"") == b""
    assert native.bgzf_decompress(b"") == b""


def test_chain_seeds_matches_python(rng):
    import pbccs_tpu.native as nat
    for trial in range(20):
        n = int(rng.integers(1, 120))
        seeds = np.stack([rng.integers(0, 200, n), rng.integers(0, 200, n)],
                         axis=1).astype(np.int32)
        k = int(rng.integers(4, 12))
        got = nat.chain_seeds(seeds, k)
        assert got is not None
        # reference numpy path (bypass the native dispatch)
        import unittest.mock as mock
        with mock.patch.object(nat, "chain_seeds", lambda *a, **kw: None):
            want = seedlib.chain_seeds(seeds, k)
        np.testing.assert_array_equal(got, want), trial


def test_chain_seeds_real_sequences(rng):
    # end-to-end: sparse_align through the native chainer gives anchors
    # ascending in both coordinates
    seq = rng.integers(0, 4, 400).astype(np.int8)
    read = np.concatenate([seq[:200], rng.integers(0, 4, 5).astype(np.int8),
                           seq[200:]])
    chain = seedlib.sparse_align(seq, read, k=8)
    assert len(chain) > 10
    assert (np.diff(chain[:, 0]) > 0).all()
    assert (np.diff(chain[:, 1]) > 0).all()
