"""Native C++ library vs the pure-Python reference implementations
(BGZF codec round trips + SDP chaining equivalence)."""

import io

import numpy as np
import pytest

from pbccs_tpu import native
from pbccs_tpu.align import seeds as seedlib
from pbccs_tpu.io.bam import BgzfReader, BgzfWriter, _BGZF_EOF

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native library unavailable")


def test_bgzf_native_compress_python_read(rng):
    payload = rng.integers(0, 256, 300_000, dtype=np.uint8).tobytes()
    packed = native.bgzf_compress(payload)
    buf = io.BytesIO(packed + _BGZF_EOF)
    rd = BgzfReader(buf)
    assert rd.read(len(payload) + 10) == payload


def test_bgzf_python_write_native_decompress(rng):
    payload = rng.integers(0, 256, 200_000, dtype=np.uint8).tobytes()
    buf = io.BytesIO()
    w = BgzfWriter(buf)
    w.write(payload)
    w.close()
    got = native.bgzf_decompress(buf.getvalue(), expected_size=len(payload) + 64)
    assert got == payload


def test_bgzf_native_roundtrip_empty():
    assert native.bgzf_compress(b"") == b""
    assert native.bgzf_decompress(b"") == b""


def test_chain_seeds_matches_python(rng):
    import pbccs_tpu.native as nat
    for trial in range(20):
        n = int(rng.integers(1, 120))
        seeds = np.stack([rng.integers(0, 200, n), rng.integers(0, 200, n)],
                         axis=1).astype(np.int32)
        k = int(rng.integers(4, 12))
        got = nat.chain_seeds(seeds, k)
        assert got is not None
        # reference numpy path (bypass the native dispatch)
        import unittest.mock as mock
        with mock.patch.object(nat, "chain_seeds", lambda *a, **kw: None):
            want = seedlib.chain_seeds(seeds, k)
        np.testing.assert_array_equal(got, want), trial


def test_chain_seeds_real_sequences(rng):
    # end-to-end: sparse_align through the native chainer gives anchors
    # ascending in both coordinates
    seq = rng.integers(0, 4, 400).astype(np.int8)
    read = np.concatenate([seq[:200], rng.integers(0, 4, 5).astype(np.int8),
                           seq[200:]])
    chain = seedlib.sparse_align(seq, read, k=8)
    assert len(chain) > 10
    assert (np.diff(chain[:, 0]) > 0).all()
    assert (np.diff(chain[:, 1]) > 0).all()


def test_native_poa_matches_python(rng):
    """The native POA engine and the pure-Python PoaGraph make identical
    add/orientation decisions and produce identical consensus + extents
    (the native engine is documented behavior-identical)."""
    import pbccs_tpu.native as nat
    from pbccs_tpu.poa.graph import PoaGraph
    from pbccs_tpu.poa.sparse import SparsePoa
    from pbccs_tpu.models.arrow.params import revcomp
    from pbccs_tpu.simulate import (
        make_transition_track, random_snr, random_template, sample_read)

    if not nat.available():
        pytest.skip("native library unavailable")

    for trial in range(10):
        tpl = random_template(rng, int(rng.integers(40, 180)))
        trans = make_transition_track(tpl, random_snr(rng))
        reads = [sample_read(rng, tpl, trans)
                 for _ in range(int(rng.integers(2, 7)))]
        reads = [revcomp(r) if rng.random() < 0.4 else r for r in reads]

        pn = SparsePoa()
        assert pn._native is not None
        pp = SparsePoa.__new__(SparsePoa)
        pp._native = None
        pp._graph = PoaGraph()
        pp._snapshot = None
        pp.read_paths = []
        pp.reverse_complemented = []

        assert [pn.orient_and_add_read(r) for r in reads] == \
            [pp.orient_and_add_read(r) for r in reads], trial
        cn, sn = pn.find_consensus(2)
        cp, sp = pp.find_consensus(2)
        np.testing.assert_array_equal(cn, cp)
        assert pn.last_consensus_path == pp.last_consensus_path
        for a, b in zip(sn, sp):
            assert a == b, trial
