"""Golden tests for the SNR-conditioned transition model (parity with
reference ContextParameterProvider.cpp:69-113 semantics)."""

import jax.numpy as jnp
import numpy as np

from pbccs_tpu.models.arrow.params import (
    CONTEXT_COEFF,
    TRANS_BRANCH,
    TRANS_DARK,
    TRANS_MATCH,
    TRANS_STICK,
    context_index,
    decode_bases,
    encode_bases,
    revcomp,
    snr_to_transition_table,
    template_transition_params,
)
from pbccs_tpu.simulate import make_transition_track


def golden_transition(ctx: int, snr: float):
    """Literal transcription of the reference formula for one context."""
    powers = np.array([1.0, snr, snr**2, snr**3])
    xb = np.exp(CONTEXT_COEFF[ctx] @ powers)  # [dark, match, stick]
    s = 1.0 + xb.sum()
    return xb[1] / s, 1.0 / s, xb[2] / s, xb[0] / s  # match, branch, stick, dark


def test_table_matches_golden():
    snr = np.array([7.0, 8.5, 6.2, 11.0])
    table = np.asarray(snr_to_transition_table(jnp.asarray(snr)))
    for ctx in range(8):
        chan = ctx % 4
        m, b, s, d = golden_transition(ctx, snr[chan])
        np.testing.assert_allclose(table[ctx], [m, b, s, d], rtol=1e-4)
        assert abs(table[ctx].sum() - 1.0) < 1e-5


def test_context_index():
    # AA context: cur==next==A -> 0 ; NA: cur!=A, next=A -> 4
    assert int(context_index(jnp.int32(0), jnp.int32(0))) == 0
    assert int(context_index(jnp.int32(3), jnp.int32(3))) == 3
    assert int(context_index(jnp.int32(1), jnp.int32(0))) == 4
    assert int(context_index(jnp.int32(0), jnp.int32(3))) == 7


def test_template_track_matches_numpy_mirror():
    rng = np.random.default_rng(0)
    tpl = rng.integers(0, 4, 40).astype(np.int8)
    snr = np.array([8.0, 9.0, 7.5, 10.0])
    track_np = make_transition_track(tpl, snr)
    table = snr_to_transition_table(jnp.asarray(snr))
    track_jax = np.asarray(template_transition_params(jnp.asarray(tpl), table))
    np.testing.assert_allclose(track_jax, track_np, rtol=1e-4, atol=1e-6)
    # final position is the zero sentinel
    assert np.all(track_jax[-1] == 0)


def test_encode_decode_revcomp():
    s = "ACGTTGCA"
    e = encode_bases(s)
    assert decode_bases(e) == s
    assert decode_bases(revcomp(e)) == "TGCAACGT"
