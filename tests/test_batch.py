"""Batched ZMW polishing: parity with the per-ZMW scorer + mesh sharding.

Pattern: the reference validates its fast kernels against a reference
implementation over random inputs (TestRecursors.cpp:291-440); here the
batched driver is validated against the per-ZMW ArrowMultiReadScorer, and
the sharded path against the unsharded one.
"""

import jax
import numpy as np
import pytest

from pbccs_tpu.models.arrow import mutations as mutlib
from pbccs_tpu.models.arrow.refine import RefineOptions
from pbccs_tpu.models.arrow.scorer import ArrowMultiReadScorer
from pbccs_tpu.parallel import BatchPolisher, make_zmw_mesh
from pbccs_tpu.parallel.batch import ZmwTask
from pbccs_tpu.simulate import simulate_zmw


def make_tasks(rng, n_zmws=3, tpl_len=80, n_passes=5):
    tasks, tpls = [], []
    for z in range(n_zmws):
        tpl, reads, strands, snr = simulate_zmw(rng, tpl_len, n_passes)
        tasks.append(ZmwTask(
            id=f"m/{z}", tpl=tpl, snr=snr, reads=reads, strands=strands,
            tstarts=[0] * len(reads), tends=[len(tpl)] * len(reads)))
        tpls.append(tpl)
    return tasks, tpls


def corrupt(rng, tpl):
    out = tpl.copy()
    pos = rng.integers(10, len(tpl) - 10)
    out[pos] = (out[pos] + 1 + rng.integers(0, 3)) % 4
    return out


@pytest.mark.slow
def test_batch_scores_match_per_zmw_scorer(rng):
    tasks, _ = make_tasks(rng, n_zmws=2, tpl_len=60, n_passes=4)
    batch = BatchPolisher(tasks)
    muts_per_zmw = [mutlib.enumerate_unique(t.tpl)[:40] for t in tasks]
    got = batch.score_mutations(muts_per_zmw)

    for z, t in enumerate(tasks):
        solo = ArrowMultiReadScorer(
            t.tpl, t.snr, list(t.reads), list(t.strands),
            list(t.tstarts), list(t.tends))
        want = solo.score_mutations(muts_per_zmw[z])
        # same active sets required for comparable sums
        assert np.array_equal(batch.active[z, : len(t.reads)],
                              solo.active[: solo.n_reads])
        np.testing.assert_allclose(got[z], want, rtol=1e-4, atol=1e-3)


def test_batch_refine_recovers_templates(rng):
    tasks, tpls = make_tasks(rng, n_zmws=3, tpl_len=70, n_passes=6)
    for t in tasks:  # polish must fix a corrupted draft
        t.tpl = corrupt(rng, t.tpl)
    batch = BatchPolisher(tasks)
    results = batch.refine(RefineOptions(max_iterations=10))
    assert all(r.converged for r in results)
    for z in range(3):
        assert np.array_equal(batch.tpls[z], tpls[z]), f"zmw {z} not recovered"
    qvs = batch.consensus_qvs()
    assert all(len(q) == len(batch.tpls[z]) for z, q in enumerate(qvs))
    assert all(q.mean() > 10 for q in qvs)


@pytest.mark.slow
def test_batch_sharded_matches_unsharded(rng):
    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"
    tasks, _ = make_tasks(rng, n_zmws=4, tpl_len=60, n_passes=4)
    muts_per_zmw = [mutlib.enumerate_unique(t.tpl)[:30] for t in tasks]

    plain = BatchPolisher(tasks)
    want = plain.score_mutations(muts_per_zmw)

    mesh = make_zmw_mesh(n_zmw=4, n_read=2)
    sharded = BatchPolisher(tasks, mesh=mesh)
    got = sharded.score_mutations(muts_per_zmw)

    assert np.array_equal(sharded.active[:4, :4], plain.active[:4, :4])
    for z in range(4):
        np.testing.assert_allclose(got[z], want[z], rtol=1e-4, atol=1e-3)


def test_batch_sharded_pallas_fills(rng, monkeypatch):
    """Mesh runs keep the Pallas fill kernel: fills run inside
    jax.shard_map per device (interpret mode on CPU), and sharded scores
    match the unsharded JAX-path scores."""
    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"
    tasks, _ = make_tasks(rng, n_zmws=4, tpl_len=60, n_passes=4)
    muts_per_zmw = [mutlib.enumerate_unique(t.tpl)[:20] for t in tasks]

    plain = BatchPolisher(tasks)
    want = plain.score_mutations(muts_per_zmw)

    from pbccs_tpu.ops.fwdbwd_pallas import fills_use_pallas

    monkeypatch.setenv("PBCCS_PALLAS", "1")
    assert fills_use_pallas()
    mesh = make_zmw_mesh(n_zmw=4, n_read=2)
    sharded = BatchPolisher(tasks, mesh=mesh)
    got = sharded.score_mutations(muts_per_zmw)

    assert np.array_equal(sharded.active[:4, :4], plain.active[:4, :4])
    for z in range(4):
        np.testing.assert_allclose(got[z], want[z], rtol=1e-4, atol=1e-3)


@pytest.mark.slow
@pytest.mark.parametrize("tpl_len", [60, 300])
def test_batch_sharded_device_refine_matches_unsharded(rng, monkeypatch,
                                                       tpl_len):
    """The sharded device-resident refinement loop (shard_map over the
    ('zmw', 'read') mesh with read-axis psum) produces the same templates,
    refine stats, and QVs as the single-device device loop.

    tpl_len=300 runs a multi-block (NB=6) bucket so the mesh path covers
    the halo-block streaming, the W(L) schedule, and the live-mask einsum
    the 60 bp bucket doesn't reach — multi-chip long-insert runs take
    this same sharded dense path (dense_score_enabled up to
    DENSE_MAX_JMAX; the mesh bail at parallel/batch.py only triggers
    beyond it)."""
    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"
    from pbccs_tpu.models.arrow.refine import RefineOptions

    tasks, _ = make_tasks(rng, n_zmws=4, tpl_len=tpl_len, n_passes=4)
    for t in tasks:  # corrupt drafts so refinement has real work
        t.tpl[30] = (t.tpl[30] + 1) % 4
    opts = RefineOptions(max_iterations=6)

    monkeypatch.setenv("PBCCS_DEVICE_REFINE", "1")
    monkeypatch.setenv("PBCCS_DENSE", "1")
    plain = BatchPolisher(tasks)
    rp = plain.refine(opts)
    qp = plain.consensus_qvs()

    mesh = make_zmw_mesh(n_zmw=4, n_read=2)
    sharded = BatchPolisher(tasks, mesh=mesh)
    rs = sharded.refine_device(opts)
    assert rs is not None, "mesh refine fell back to the host loop"
    qs = sharded.consensus_qvs()

    for z in range(4):
        assert rp[z].converged == rs[z].converged
        np.testing.assert_array_equal(plain.tpls[z], sharded.tpls[z])
        np.testing.assert_array_equal(qp[z], qs[z])


def test_batch_global_zscores_finite(rng):
    tasks, _ = make_tasks(rng, n_zmws=2, tpl_len=60, n_passes=4)
    batch = BatchPolisher(tasks)
    gz = batch.global_zscores()
    assert gz.shape == (2,)
    assert np.isfinite(gz).all()


@pytest.mark.slow
def test_partial_refill_matches_full(rng):
    """Refilling only changed ZMWs after apply_mutations produces the same
    templates, QVs, and convergence as the always-full rebuild."""
    from pbccs_tpu.models.arrow.refine import RefineOptions

    def build(seed):
        r = np.random.default_rng(seed)
        tasks = []
        for z in range(6):
            tpl, reads, strands, snr = simulate_zmw(r, 120, 5)
            draft = tpl.copy()
            draft[30 + z] = (draft[30 + z] + 1) % 4
            tasks.append(ZmwTask(f"pr/{z}", draft, snr, reads, strands,
                                 [0] * len(reads), [len(draft)] * len(reads)))
        return tasks

    pol_full = BatchPolisher(build(7))
    orig = BatchPolisher._setup_partial
    BatchPolisher._setup_partial = \
        lambda self, ch: BatchPolisher._setup(self, first=False)
    try:
        res_full = pol_full.refine(RefineOptions(max_iterations=6))
        qv_full = pol_full.consensus_qvs()
    finally:
        BatchPolisher._setup_partial = orig

    pol_part = BatchPolisher(build(7))
    res_part = pol_part.refine(RefineOptions(max_iterations=6))
    qv_part = pol_part.consensus_qvs()

    for z in range(6):
        np.testing.assert_array_equal(pol_full.tpls[z], pol_part.tpls[z])
        np.testing.assert_array_equal(qv_full[z], qv_part[z])
        assert res_full[z].converged == res_part[z].converged


@pytest.mark.slow
def test_tiny_window_fallback_matches_per_zmw(rng):
    """Reads whose template window is shorter than MIN_FAST_EDGE_WLEN score
    boundary mutations by full refill (the fallback pair path); decisions
    must still match the per-ZMW scorer."""
    from pbccs_tpu.parallel.batch import MIN_FAST_EDGE_WLEN

    tpl, reads, strands, snr = simulate_zmw(rng, 60, 5)
    tstarts = [0] * len(reads)
    tends = [len(tpl)] * len(reads)
    # clip one read to a tiny window at the template start
    w = MIN_FAST_EDGE_WLEN - 2
    reads = list(reads)
    reads[1] = reads[1][:w]
    tends[1] = w

    task = ZmwTask("tiny/0", tpl, snr, reads, strands, tstarts, tends)
    pol = BatchPolisher([task])
    sc = ArrowMultiReadScorer(tpl, snr, reads, strands, tstarts, tends)

    muts = mutlib.enumerate_unique(tpl)
    batch_scores = pol.score_mutations([muts])[0]
    serial_scores = sc.score_mutations(muts)
    np.testing.assert_allclose(batch_scores, serial_scores, atol=2e-3)
