"""End-to-end CCS on the reference's real subread fixture.

The reference uses tests/data/m140905_..._X0.fasta (10 real subread passes
of one ZMW, ~600bp insert) to validate its POA stage
(reference tests/TestSparsePoa.cpp:150-170, TestUtils.cpp:39-54); here the
same real data drives the full filter -> draft -> polish -> QV pipeline
through the ccs-compatible CLI, FASTA in / FASTA out."""

import os
import subprocess
import sys

import numpy as np
import pytest

FIXTURE = ("/root/reference/tests/data/m140905_042212_sidney_"
           "c100564852550000001823085912221377_s1_X0.fasta")

pytestmark = pytest.mark.skipif(not os.path.exists(FIXTURE),
                                reason="reference fixture unavailable")


@pytest.mark.slow
def test_ccs_on_real_zmw(tmp_path):
    from pbccs_tpu.cli import run
    from pbccs_tpu.io.fasta import read_fasta

    out = str(tmp_path / "out.fasta")
    report = str(tmp_path / "report.csv")
    rc = run([f"--reportFile={report}", "--skipChemistryCheck",
              "--minPasses=3", out, FIXTURE])
    assert rc == 0
    recs = list(read_fasta(out))
    assert len(recs) == 1
    name, css = recs[0]
    assert "6251" in name
    # the insert is ~600bp (pass lengths 480-633 with adapters trimmed)
    assert 500 <= len(css) <= 700

    # every full pass should align to the consensus at subread identity
    # or better (>=80% matches over the consensus span)
    from pbccs_tpu.align.pairwise import AlignConfig, SEMIGLOBAL, align as nw_align
    from pbccs_tpu.models.arrow.params import BASES, encode_bases, revcomp
    cfg = AlignConfig(mode=SEMIGLOBAL)
    idents = []
    for rname, seq in read_fasta(FIXTURE):
        if len(seq) < 400:      # partial last pass
            continue
        rc_seq = "".join(BASES[c] for c in revcomp(encode_bases(seq)))
        best = 0.0
        for cand in (seq, rc_seq):
            aln = nw_align(cand, css, cfg)
            best = max(best, aln.transcript.count("M") / max(len(css), 1))
        idents.append(best)
    assert len(idents) >= 9
    assert np.mean(idents) > 0.80, idents


def test_polish_matches_reference_cpp_on_real_zmw():
    """Cross-validate the polish stage against the reference's own compiled
    C++ Arrow implementation on the real ZMW: same prepared inputs (our
    draft stage), consensus must be BIT-IDENTICAL (the round-2 simulated
    cross-validation protocol, now on real data).

    QV strings may differ in two characterized ways: +-1 knife-edge
    rounding anywhere (f32 scoring vs double), and larger deviations ONLY
    at read-window boundary positions (POA extents of partial passes),
    where our fixed-shape edge fast paths and the reference's adaptive
    extend-to-end/from-begin land on different-but-valid band contents."""
    import subprocess
    import sys as _sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    refbench = os.path.join(repo, "native", "refbench", "build", "refbench")
    if not os.path.exists(refbench):
        r = subprocess.run(["make", "-C",
                            os.path.join(repo, "native", "refbench")],
                           capture_output=True, text=True)
        if r.returncode != 0 or not os.path.exists(refbench):
            pytest.skip("refbench build unavailable")

    _sys.path.insert(0, os.path.join(repo, "tools"))
    from crossval_real import polish_ours, polish_reference, prepare

    prep, settings = prepare()
    ours, our_q, res, windows = polish_ours(prep, settings)
    ref, ref_q, stats = polish_reference(prep, settings)

    assert res.converged and stats["converged"] == 1
    assert ours == ref, "consensus differs from the reference C++"

    # window bounds in the FINAL consensus frame (polish_ours remaps the
    # draft-frame POA extents through every applied indel)
    boundary = {0, len(ours) - 1}
    for ts, te in windows:
        boundary |= {ts, ts - 1, te - 1, te}
    diffs = [(i, ord(a) - 33, ord(b) - 33)
             for i, (a, b) in enumerate(zip(our_q, ref_q)) if a != b]
    assert len(diffs) <= 0.02 * len(ours), diffs
    for i, qa, qb in diffs:
        assert abs(qa - qb) <= 1 or i in boundary, (i, qa, qb)
