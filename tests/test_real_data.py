"""End-to-end CCS on the reference's real subread fixture.

The reference uses tests/data/m140905_..._X0.fasta (10 real subread passes
of one ZMW, ~600bp insert) to validate its POA stage
(reference tests/TestSparsePoa.cpp:150-170, TestUtils.cpp:39-54); here the
same real data drives the full filter -> draft -> polish -> QV pipeline
through the ccs-compatible CLI, FASTA in / FASTA out."""

import os
import subprocess
import sys

import numpy as np
import pytest

FIXTURE = ("/root/reference/tests/data/m140905_042212_sidney_"
           "c100564852550000001823085912221377_s1_X0.fasta")

pytestmark = pytest.mark.skipif(not os.path.exists(FIXTURE),
                                reason="reference fixture unavailable")


def test_ccs_on_real_zmw(tmp_path):
    from pbccs_tpu.cli import run
    from pbccs_tpu.io.fasta import read_fasta

    out = str(tmp_path / "out.fasta")
    report = str(tmp_path / "report.csv")
    rc = run([f"--reportFile={report}", "--skipChemistryCheck",
              "--minPasses=3", out, FIXTURE])
    assert rc == 0
    recs = list(read_fasta(out))
    assert len(recs) == 1
    name, css = recs[0]
    assert "6251" in name
    # the insert is ~600bp (pass lengths 480-633 with adapters trimmed)
    assert 500 <= len(css) <= 700

    # every full pass should align to the consensus at subread identity
    # or better (>=80% matches over the consensus span)
    from pbccs_tpu.align.pairwise import AlignConfig, SEMIGLOBAL, align as nw_align
    from pbccs_tpu.models.arrow.params import BASES, encode_bases, revcomp
    cfg = AlignConfig(mode=SEMIGLOBAL)
    idents = []
    for rname, seq in read_fasta(FIXTURE):
        if len(seq) < 400:      # partial last pass
            continue
        rc_seq = "".join(BASES[c] for c in revcomp(encode_bases(seq)))
        best = 0.0
        for cand in (seq, rc_seq):
            aln = nw_align(cand, css, cfg)
            best = max(best, aln.transcript.count("M") / max(len(css), 1))
        idents.append(best)
    assert len(idents) >= 9
    assert np.mean(idents) > 0.80, idents
