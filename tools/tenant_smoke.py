#!/usr/bin/env python
"""Multi-tenant edge smoke for the tier-1 gate: a 2-tenant TLS fleet
with per-tenant fair queuing, token auth on every surface, and
SLO-burn load shedding.

Legs:

  baseline  offline process_chunks over tenant B's workload (the
            byte-identity reference), computed in-process
  edges     every front-door surface -- replica port, router port, the
            HTTPS metrics scrape, and the fleet admin verb -- drops
            PLAINTEXT clients at the handshake and answers
            token-less/unknown-token frames with a structured
            `unauthorized` (session survives; zero unauthenticated
            frames are ever accepted)
  noisy     tenant A floods 4x its in-flight quota on one session while
            tenant B submits its cell: B completes 100% within the SLO
            and byte-identical to offline, B is never rejected, A's
            over-quota spill gets structured `overloaded` replies that
            ALL carry retry_after_ms, and the router's tenancy
            accounting (status rows + ccs_tenant_* series on the
            federated HTTPS scrape) matches what happened
  shed      a second 1-replica fleet with an impossible --sloP99Ms and
            --shedBurnRate 0.5: once the probe-fed burn meter crosses
            the threshold the router sheds priority-1 work with
            retry_after_ms while priority-0 work still completes

The workload reuses the chaos-cell geometry (tpl 60, 5 passes, seed
20260803) so its compiled shapes are already in the persistent cache
from the chaos/fuzz/fleet smokes.

Run:  JAX_PLATFORMS=cpu python tools/tenant_smoke.py
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, ".")  # runnable as tools/tenant_smoke.py from the repo root

N_B_ZMWS = 6
N_FLOOD_FACTOR = 4          # tenant A submits 4x its in-flight quota
A_QUOTA = 2
A_QUEUE_DEPTH = 2
B_SLO_S = 300.0             # wall bound per B request under A's flood
REPLY_TIMEOUT_S = 600.0
RETRY_MS = 750.0
SHED_RETRY_MS = 500.0

TOKEN_A = "smoke-tenant-a"
TOKEN_B = "smoke-tenant-b"
TOKEN_LINK = "smoke-router-link"


def check(name: str, ok: bool, detail: str = "") -> None:
    print(f"  {'PASS' if ok else 'FAIL'}  {name}"
          + (f"  ({detail})" if detail else ""), flush=True)
    if not ok:
        raise SystemExit(f"tenant smoke failed: {name} {detail}")


def make_workload(n, prefix):
    from pbccs_tpu.models.arrow.params import decode_bases
    from pbccs_tpu.pipeline import Chunk, Subread
    from pbccs_tpu.simulate import simulate_zmw

    rng = np.random.default_rng(20260803)
    chunks, wires = [], []
    for i in range(n):
        _, reads, _, snr = simulate_zmw(rng, 60, 5)
        zid = f"{prefix}/{i}"
        chunks.append(Chunk(
            zid, [Subread(f"{zid}/{k}", r) for k, r in enumerate(reads)],
            snr))
        wires.append({"id": zid, "snr": [float(s) for s in snr],
                      "reads": [{"seq": decode_bases(r)} for r in reads]})
    return chunks, wires


def make_edge_material(tmp: str) -> tuple[str, str, str]:
    """Self-signed EC cert (its own CA) + the 3-tenant token file."""
    cert, key = os.path.join(tmp, "cert.pem"), os.path.join(tmp, "key.pem")
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "ec", "-pkeyopt",
         "ec_paramgen_curve:prime256v1", "-nodes", "-keyout", key,
         "-out", cert, "-days", "2", "-subj", "/CN=localhost"],
        check=True, capture_output=True)
    tokens = os.path.join(tmp, "tokens.json")
    with open(tokens, "w") as f:
        json.dump({"tenants": [
            {"name": "tenantA", "token": TOKEN_A,
             "max_inflight": A_QUOTA, "priority": 1, "weight": 1},
            {"name": "tenantB", "token": TOKEN_B,
             "max_inflight": 4, "priority": 0, "weight": 2},
            {"name": "_router", "token": TOKEN_LINK,
             "priority": 0, "trusted": True},
        ]}, f)
    return cert, key, tokens


def spawn_ready(subcmd_args, marker):
    proc = subprocess.Popen(
        [sys.executable, "-m", "pbccs_tpu.cli"] + subcmd_args,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
    preamble: list[str] = []
    line = proc.stdout.readline()
    while line and not line.startswith(marker):
        preamble.append(line)
        line = proc.stdout.readline()
    if not line:
        proc.kill()
        raise SystemExit(f"{marker} never seen (rc={proc.poll()})")
    return proc, int(line.split()[2]), preamble


def spawn_replica(cert, key, tokens, slo_ms=0.0):
    argv = ["serve", "--port", "0", "--maxBatch", "4", "--maxWaitMs", "250",
            "--maxInflightPerSession", "256", "--drainTimeout", "300",
            "--logLevel", "ERROR", "--tlsCert", cert, "--tlsKey", key,
            "--authTokens", tokens]
    if slo_ms:
        argv += ["--sloP99Ms", str(slo_ms)]
    proc, port, _pre = spawn_ready(argv, "CCS-SERVE-READY")
    return proc, port


def spawn_router(ports, cert, key, tokens, shed_burn=0.0,
                 shed_retry_ms=SHED_RETRY_MS):
    argv = ["router", "--port", "0", "--logLevel", "ERROR",
            "--routerHealthInterval", "0.5", "--routerHealthTimeout", "3",
            "--metricsPort", "-1",
            "--tlsCert", cert, "--tlsKey", key, "--authTokens", tokens,
            "--tlsCa", cert, "--authToken", TOKEN_LINK,
            "--tenantQueueDepth", str(A_QUEUE_DEPTH),
            "--shedRetryMs", str(RETRY_MS)]
    if shed_burn:
        argv += ["--shedBurnRate", str(shed_burn),
                 "--shedRetryMs", str(shed_retry_ms)]
    for p in ports:
        argv += ["--replica", f"127.0.0.1:{p}"]
    proc, port, preamble = spawn_ready(argv, "CCS-ROUTER-READY")
    metrics_port = next(
        (int(line.split()[2]) for line in preamble
         if line.startswith("CCS-METRICS-READY")), -1)
    return proc, port, metrics_port


def tls_conn(port, cert, timeout=REPLY_TIMEOUT_S):
    from pbccs_tpu.serve import tenancy

    ctx = tenancy.client_ssl_context(cert)
    s = socket.create_connection(("127.0.0.1", port), timeout=timeout)
    return ctx.wrap_socket(s, server_hostname="127.0.0.1")


def tls_verb(port, cert, frame, timeout=60.0):
    with tls_conn(port, cert, timeout) as c:
        c.sendall(json.dumps(frame).encode() + b"\n")
        rf = c.makefile("rb")
        while True:
            msg = json.loads(rf.readline())
            if msg.get("id") == frame.get("id"):
                return msg


def https_get_metrics(port, cert) -> str:
    with tls_conn(port, cert, timeout=60.0) as c:
        c.sendall(b"GET /metrics HTTP/1.0\r\n\r\n")
        data = b""
        while True:
            chunk = c.recv(65536)
            if not chunk:
                break
            data += chunk
    head, _, body = data.partition(b"\r\n\r\n")
    check("metrics: HTTPS scrape answers 200", b"200 OK" in head,
          head.split(b"\r\n")[0].decode(errors="replace"))
    return body.decode()


# ------------------------------------------------------------ edge surfaces

def leg_edge_surfaces(replica_port, router_port, metrics_port, cert):
    print("== leg: every edge surface rejects plaintext + "
          "unauthenticated ==", flush=True)
    # plaintext clients die at the handshake on both NDJSON front doors
    for name, port in (("serve", replica_port), ("router", router_port)):
        raw = socket.create_connection(("127.0.0.1", port), timeout=30.0)
        raw.settimeout(30.0)
        raw.sendall(b'{"verb":"ping","id":"p"}\n')
        try:
            data = raw.recv(4096)
        except OSError:
            data = b""
        raw.close()
        check(f"{name}: plaintext client dropped", data == b"",
              f"got {data[:40]!r}")

    # token-less / unknown-token frames get a structured `unauthorized`
    # (the session survives and works once the token appears)
    for name, port, tok in (("serve", replica_port, TOKEN_LINK),
                            ("router", router_port, TOKEN_B)):
        with tls_conn(port, cert, timeout=60.0) as c:
            rf = c.makefile("rb")
            c.sendall(b'{"verb":"status","id":"u1"}\n')
            msg = json.loads(rf.readline())
            check(f"{name}: token-less frame unauthorized",
                  msg.get("type") == "error"
                  and msg.get("code") == "unauthorized", str(msg)[:90])
            c.sendall(b'{"verb":"status","id":"u2","auth":"bogus"}\n')
            msg = json.loads(rf.readline())
            check(f"{name}: unknown token unauthorized",
                  msg.get("code") == "unauthorized")
            c.sendall(json.dumps({"verb": "ping", "id": "p",
                                  "auth": tok}).encode() + b"\n")
            check(f"{name}: session survives once authenticated",
                  json.loads(rf.readline()).get("type") == "pong")

    # the fleet admin verb sits behind the same gate
    msg = tls_verb(router_port, cert,
                   {"verb": "fleet", "id": "f1", "action": "list"})
    check("fleet verb: token-less frame unauthorized",
          msg.get("code") == "unauthorized")
    msg = tls_verb(router_port, cert,
                   {"verb": "fleet", "id": "f2", "action": "list",
                    "auth": TOKEN_LINK})
    check("fleet verb: answers with the trusted token",
          msg.get("type") == "fleet", str(msg)[:90])

    # the metrics scrape is HTTPS-only: no plaintext surface anywhere
    raw = socket.create_connection(("127.0.0.1", metrics_port),
                                   timeout=30.0)
    raw.settimeout(30.0)
    raw.sendall(b"GET /metrics HTTP/1.0\r\n\r\n")
    try:
        data = raw.recv(4096)
    except OSError:
        data = b""
    raw.close()
    check("metrics: plaintext scrape rejected", b"200 OK" not in data,
          f"got {data[:40]!r}")


# ----------------------------------------------------------- noisy neighbor

def leg_noisy_neighbor(router_port, metrics_port, cert, wires_a, wires_b,
                       offline_out):
    from pbccs_tpu.serve.client import CcsClient

    print("== leg: noisy neighbor (A floods 4x quota, B rides fair "
          "queue) ==", flush=True)
    n_flood = N_FLOOD_FACTOR * A_QUOTA * len(wires_a)
    a = tls_conn(router_port, cert)
    arf = a.makefile("rb")
    a_ids = [f"a{i}" for i in range(n_flood)]
    for i, rid in enumerate(a_ids):
        a.sendall(json.dumps(
            {"verb": "submit", "id": rid, "zmw": wires_a[i % len(wires_a)],
             "auth": TOKEN_A}).encode() + b"\n")

    # B submits its whole cell while A's flood is in the queue
    cli = CcsClient("127.0.0.1", router_port, timeout=REPLY_TIMEOUT_S,
                    tls_ca=cert, auth_token=TOKEN_B)
    handles = [(time.monotonic(), cli.submit_wire(z)) for z in wires_b]
    lat, got_b = [], {}
    for t0, h in handles:
        msg = h.reply(REPLY_TIMEOUT_S)
        lat.append(time.monotonic() - t0)
        check("noisy: B reply is a Success result",
              msg.get("type") == "result"
              and msg.get("status") == "Success",
              str(msg.get("status") or msg.get("code")))
        got_b[msg["zmw"]] = (msg["sequence"], msg["qual"])
    check("noisy: B byte-identical to offline", got_b == offline_out,
          f"{len(got_b)}/{len(offline_out)} matched")
    p99 = max(lat)
    check("noisy: B p99 within SLO under A's flood", p99 <= B_SLO_S,
          f"p99={p99:.1f}s (SLO {B_SLO_S:.0f}s)")

    # drain A's replies: every over-quota spill is a structured
    # overloaded WITH a retry hint, and the admitted ones complete
    a_replies = {}
    while len(a_replies) < n_flood:
        msg = json.loads(arf.readline())
        if msg.get("id") in set(a_ids):
            a_replies[msg["id"]] = msg
    a.close()
    rejected = [m for m in a_replies.values() if m.get("type") == "error"]
    completed = [m for m in a_replies.values() if m.get("type") == "result"]
    check("noisy: A over-quota spill rejected",
          len(rejected) >= n_flood - A_QUOTA - A_QUEUE_DEPTH,
          f"{len(rejected)} rejected / {len(completed)} completed")
    check("noisy: every A reject is overloaded + retry_after_ms",
          all(m.get("code") == "overloaded"
              and isinstance(m.get("retry_after_ms"), (int, float))
              and m["retry_after_ms"] > 0 for m in rejected),
          f"hint={rejected[0].get('retry_after_ms') if rejected else '-'}ms")

    # the tenancy accounting saw all of it
    st = cli.status(60.0)
    ten = st.get("tenancy") or {}
    rows = {r["name"]: r for r in ten.get("tenants", [])}
    check("noisy: status carries per-tenant rows",
          {"tenantA", "tenantB"} <= set(rows), str(sorted(rows)))
    check("noisy: B never rejected, whole cell completed",
          rows["tenantB"]["rejected"] == 0
          and rows["tenantB"]["completed"] >= len(wires_b),
          str(rows["tenantB"]))
    check("noisy: A's spill is in its OWN row",
          rows["tenantA"]["rejected"] >= len(rejected) - 1
          and rows["tenantA"]["completed"] >= 1, str(rows["tenantA"]))
    cli.close()

    body = https_get_metrics(metrics_port, cert)
    for needle in ('ccs_tenant_requests_total{tenant="tenantA"}',
                   'ccs_tenant_requests_total{tenant="tenantB"}',
                   'ccs_tenant_rejects_total{',
                   "ccs_router_fleet_burn_rate"):
        check(f"noisy: scrape carries {needle.split('{')[0]}",
              needle.split("{")[0] in body
              and (("{" not in needle) or any(
                  line.startswith(needle.split('}')[0])
                  for line in body.splitlines())), needle)


# -------------------------------------------------------------------- shed

def leg_shed(tmp, cert, key, tokens, wires_b):
    print("== leg: SLO-burn shedding (impossible SLO, threshold 0.5) ==",
          flush=True)
    replica_proc, replica_port = spawn_replica(cert, key, tokens,
                                               slo_ms=0.001)
    router_proc, router_port, _m = spawn_router(
        [replica_port], cert, key, tokens, shed_burn=0.5)
    try:
        # priority-0 traffic generates violations (every request misses
        # a 1-microsecond SLO) that ride probe status into the meter
        for i, z in enumerate(wires_b[:3]):
            msg = tls_verb(router_port, cert,
                           {"verb": "submit", "id": f"warm{i}", "zmw": z,
                            "auth": TOKEN_B}, timeout=REPLY_TIMEOUT_S)
            check("shed: warmup (priority 0) completes",
                  msg.get("status") == "Success",
                  str(msg.get("status") or msg.get("code")))
        deadline = time.monotonic() + 60.0
        shedding, burn = False, 0.0
        while time.monotonic() < deadline and not shedding:
            st = tls_verb(router_port, cert,
                          {"verb": "status", "id": "st",
                           "auth": TOKEN_B})
            ten = st.get("tenancy") or {}
            burn = ten.get("burn_rate", 0.0)
            shedding = bool(ten.get("shedding"))
            if not shedding:
                time.sleep(0.25)
        check("shed: probe-fed burn meter crossed the threshold",
              shedding and burn >= 0.5, f"burn={burn}")
        # priority-1 work is now shed with the configured hint...
        msg = tls_verb(router_port, cert,
                       {"verb": "submit", "id": "s1", "zmw": wires_b[0],
                        "auth": TOKEN_A}, timeout=60.0)
        check("shed: priority-1 submit shed with retry hint",
              msg.get("code") == "overloaded"
              and msg.get("retry_after_ms") == SHED_RETRY_MS
              and "shedding" in msg.get("error", ""), str(msg)[:110])
        # ...while priority-0 work still completes
        msg = tls_verb(router_port, cert,
                       {"verb": "submit", "id": "s0", "zmw": wires_b[0],
                        "auth": TOKEN_B}, timeout=REPLY_TIMEOUT_S)
        check("shed: priority-0 submit still completes",
              msg.get("status") == "Success",
              str(msg.get("status") or msg.get("code")))
    finally:
        for proc in (router_proc, replica_proc):
            if proc.poll() is None:
                proc.kill()
                proc.wait(10)


def main() -> int:
    from pbccs_tpu.pipeline import ConsensusSettings, process_chunks
    from pbccs_tpu.runtime.cache import enable_compilation_cache
    from pbccs_tpu.runtime.logging import Logger, LogLevel

    enable_compilation_cache()
    Logger.default(Logger(level=LogLevel.ERROR))
    chunks_b, wires_b = make_workload(N_B_ZMWS, "tenantB")
    _chunks_a, wires_a = make_workload(2, "tenantA")

    print("== baseline (offline process_chunks, tenant B's cell) ==",
          flush=True)
    t0 = time.monotonic()
    offline = process_chunks(list(chunks_b), ConsensusSettings())
    offline_out = {r.id: (r.sequence, r.qualities)
                   for r in offline.results}
    check("baseline yields all successes", len(offline_out) == N_B_ZMWS,
          f"{len(offline_out)}/{N_B_ZMWS} in {time.monotonic() - t0:.0f}s")

    tmp = tempfile.mkdtemp(prefix="tenant_smoke_")
    cert, key, tokens = make_edge_material(tmp)
    replicas = [spawn_replica(cert, key, tokens) for _ in range(2)]
    ports = [port for _, port in replicas]
    router_proc, router_port, metrics_port = spawn_router(
        ports, cert, key, tokens)
    try:
        leg_edge_surfaces(ports[0], router_port, metrics_port, cert)
        leg_noisy_neighbor(router_port, metrics_port, cert,
                           wires_a, wires_b, offline_out)
        print("== router drains cleanly ==", flush=True)
        import signal

        router_proc.send_signal(signal.SIGTERM)
        rc = router_proc.wait(timeout=60)
        check("router exited 0 on SIGTERM", rc == 0, f"exit {rc}")
    finally:
        for proc, _ in replicas:
            if proc.poll() is None:
                proc.kill()
                proc.wait(10)
        if router_proc.poll() is None:
            router_proc.kill()
            router_proc.wait(10)

    leg_shed(tmp, cert, key, tokens, wires_b)
    print("tenant smoke: all checks passed", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
