#!/usr/bin/env python
"""Observability smoke gate: run a tiny simulated workload through the
CLI with --trace-out and validate the emitted Chrome-trace JSON schema,
then validate the FLEET-trace merge schema (tools/trace_merge.py) over
two in-process tracers exchanging wire trace context.

Part of tier-1 (tools/tier1.sh + .github/workflows/tier1.yml): the trace
export is an interface later perf PRs read, so its shape is pinned in CI
-- traceEvents present, complete ("X") events with ts/dur/pid/tid, the
span tree covering filter -> draft -> polish -> emit, device-wait
attribution on every span, and parent links that resolve.  The fleet leg
pins the MERGED schema: one pid + process_name row per process,
wall-clock-rebased timelines, remote_parent links resolving across
processes into one connected tree per trace_id, and dropped/open-span
metadata surviving the merge.

Exit 0 on success; prints the failure and exits 1 otherwise.

Usage: JAX_PLATFORMS=cpu python tools/obs_smoke.py
"""

from __future__ import annotations

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# tiny shapes + the host refinement loop: this is a schema gate, not a
# perf run, so keep the compile menu as small as possible on CPU
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PBCCS_DEVICE_REFINE", "0")

REQUIRED_SPANS = {"filter", "draft", "polish", "emit"}
EVENT_FIELDS = {"name", "ph", "ts", "dur", "pid", "tid", "args"}


def make_workload(path: str, n_zmws: int = 3, tpl_len: int = 60,
                  n_passes: int = 4) -> None:
    import numpy as np

    from pbccs_tpu.models.arrow.params import decode_bases
    from pbccs_tpu.simulate import simulate_zmw

    rng = np.random.default_rng(20260803)
    with open(path, "w") as f:
        for z in range(n_zmws):
            _, reads, _, _ = simulate_zmw(rng, tpl_len, n_passes)
            start = 0
            for read in reads:
                seq = decode_bases(read)
                f.write(f">smoke/{z}/{start}_{start + len(seq)}\n{seq}\n")
                start += len(seq) + 20


def validate_trace(trace: dict) -> list[str]:
    problems = []
    events = trace.get("traceEvents")
    if not isinstance(events, list) or not events:
        return ["traceEvents missing or empty"]
    ids = {ev.get("id") for ev in events}
    for ev in events:
        missing = EVENT_FIELDS - set(ev)
        if missing:
            problems.append(f"event {ev.get('name')!r} missing {missing}")
            continue
        if ev["ph"] != "X":
            problems.append(f"event {ev['name']!r}: ph={ev['ph']!r} != 'X'")
        if ev["dur"] < 0 or ev["ts"] < 0:
            problems.append(f"event {ev['name']!r}: negative ts/dur")
        if "device_wait_ms" not in ev["args"]:
            problems.append(f"event {ev['name']!r}: no device_wait_ms")
        parent = ev["args"].get("parent")
        if parent is not None and parent not in ids:
            problems.append(f"event {ev['name']!r}: dangling parent "
                            f"{parent}")
    names = {ev["name"] for ev in events}
    missing_spans = REQUIRED_SPANS - names
    if missing_spans:
        problems.append(f"required spans absent: {sorted(missing_spans)} "
                        f"(got {sorted(names)})")
    # device-wait attribution must land somewhere inside polish
    polish = [ev for ev in events if ev["name"].startswith("polish")]
    if polish and not any(ev["args"]["device_wait_ms"] > 0 for ev in polish):
        problems.append("no polish span carries device-wait attribution")
    return problems


def validate_fleet_merge() -> list[str]:
    """The fleet-trace-schema leg: a simulated router + replica pair
    exchange wire trace context in-process, and the merged doc must
    carry the multi-process schema fleet_smoke and dashboards key on."""
    from pbccs_tpu.obs import trace as obs_trace

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import trace_merge

    problems: list[str] = []
    router = obs_trace.Tracer(tag="router")
    replica = obs_trace.Tracer(tag="rep1", max_spans=3)
    tid = obs_trace.new_trace_id()
    # replica-side spans parent under the router's per-request span id
    with replica.span("serve.prep",
                      ctx={"trace_id": tid, "span_id": "rt-q1"}):
        with replica.span("serve.polish"):
            pass
    with replica.span("spilled", i=0):       # left open at capture
        with replica.span("dropped-by-cap"):  # past max_spans: dropped
            pass
        replica_doc = replica.to_chrome()
    router.add_span("router.request", 0.005,
                    ctx={"trace_id": tid, "span_id": "cl-0"},
                    span_id="rt-q1", replica="rep1")
    merged = trace_merge.merge_docs([("router", router.to_chrome()),
                                     ("replica rep1", replica_doc)])

    metas = [ev for ev in merged["traceEvents"]
             if ev.get("ph") == "M" and ev.get("name") == "process_name"]
    if {m["args"]["name"] for m in metas} != {"router", "replica rep1"}:
        problems.append(f"process_name metadata wrong: {metas}")
    pids = {ev["pid"] for ev in merged["traceEvents"]
            if ev.get("ph") == "X"}
    if len(pids) != 2:
        problems.append(f"expected 2 pids, got {sorted(pids)}")
    report = trace_merge.request_trees(merged)
    tree = report.get(tid)
    if tree is None:
        problems.append(f"trace {tid} missing from report {report}")
    else:
        if tree["components"] != 1:
            problems.append(f"trace {tid} not connected: {tree}")
        if len(tree["processes"]) != 2:
            problems.append(f"trace {tid} did not cross processes: {tree}")
    if merged["meta"].get("dropped_spans", 0) < 1:
        problems.append("dropped_spans did not survive the merge")
    if merged["meta"].get("open_spans", 0) < 1:
        problems.append("open_spans did not survive the merge")
    open_ev = [ev for ev in merged["traceEvents"]
               if ev.get("args", {}).get("open")]
    if not open_ev or any(ev["dur"] <= 0 for ev in open_ev):
        problems.append("open span not tagged with a capture-time "
                        f"duration: {open_ev}")
    flows = [ev for ev in merged["traceEvents"] if ev.get("ph") == "s"]
    if not flows:
        problems.append("no flow event links the cross-process parent")
    return problems


def main() -> int:
    from pbccs_tpu import cli

    tmp = tempfile.mkdtemp(prefix="pbccs_obs_smoke_")
    fasta = os.path.join(tmp, "subreads.fasta")
    trace_path = os.path.join(tmp, "trace.json")
    make_workload(fasta)
    rc = cli.run([os.path.join(tmp, "out.fasta"), fasta,
                  "--skipChemistryCheck", "--zmws", "all",
                  "--reportFile", os.path.join(tmp, "report.csv"),
                  "--trace-out", trace_path])
    if rc != 0:
        print(f"obs_smoke: cli.run failed rc={rc}", file=sys.stderr)
        return 1
    with open(trace_path) as f:
        trace = json.load(f)
    problems = validate_trace(trace)
    if problems:
        for p in problems:
            print(f"obs_smoke: {p}", file=sys.stderr)
        return 1
    problems = validate_fleet_merge()
    if problems:
        for p in problems:
            print(f"obs_smoke (fleet merge): {p}", file=sys.stderr)
        return 1
    n = len(trace["traceEvents"])
    print(f"obs_smoke: OK ({n} spans, schema valid, "
          f"spans cover {sorted(REQUIRED_SPANS)}; fleet-merge schema "
          "valid)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
