#!/usr/bin/env python
"""Observability smoke gate: run a tiny simulated workload through the
CLI with --trace-out and validate the emitted Chrome-trace JSON schema.

Part of tier-1 (tools/tier1.sh + .github/workflows/tier1.yml): the trace
export is an interface later perf PRs read, so its shape is pinned in CI
-- traceEvents present, complete ("X") events with ts/dur/pid/tid, the
span tree covering filter -> draft -> polish -> emit, device-wait
attribution on every span, and parent links that resolve.

Exit 0 on success; prints the failure and exits 1 otherwise.

Usage: JAX_PLATFORMS=cpu python tools/obs_smoke.py
"""

from __future__ import annotations

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# tiny shapes + the host refinement loop: this is a schema gate, not a
# perf run, so keep the compile menu as small as possible on CPU
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PBCCS_DEVICE_REFINE", "0")

REQUIRED_SPANS = {"filter", "draft", "polish", "emit"}
EVENT_FIELDS = {"name", "ph", "ts", "dur", "pid", "tid", "args"}


def make_workload(path: str, n_zmws: int = 3, tpl_len: int = 60,
                  n_passes: int = 4) -> None:
    import numpy as np

    from pbccs_tpu.models.arrow.params import decode_bases
    from pbccs_tpu.simulate import simulate_zmw

    rng = np.random.default_rng(20260803)
    with open(path, "w") as f:
        for z in range(n_zmws):
            _, reads, _, _ = simulate_zmw(rng, tpl_len, n_passes)
            start = 0
            for read in reads:
                seq = decode_bases(read)
                f.write(f">smoke/{z}/{start}_{start + len(seq)}\n{seq}\n")
                start += len(seq) + 20


def validate_trace(trace: dict) -> list[str]:
    problems = []
    events = trace.get("traceEvents")
    if not isinstance(events, list) or not events:
        return ["traceEvents missing or empty"]
    ids = {ev.get("id") for ev in events}
    for ev in events:
        missing = EVENT_FIELDS - set(ev)
        if missing:
            problems.append(f"event {ev.get('name')!r} missing {missing}")
            continue
        if ev["ph"] != "X":
            problems.append(f"event {ev['name']!r}: ph={ev['ph']!r} != 'X'")
        if ev["dur"] < 0 or ev["ts"] < 0:
            problems.append(f"event {ev['name']!r}: negative ts/dur")
        if "device_wait_ms" not in ev["args"]:
            problems.append(f"event {ev['name']!r}: no device_wait_ms")
        parent = ev["args"].get("parent")
        if parent is not None and parent not in ids:
            problems.append(f"event {ev['name']!r}: dangling parent "
                            f"{parent}")
    names = {ev["name"] for ev in events}
    missing_spans = REQUIRED_SPANS - names
    if missing_spans:
        problems.append(f"required spans absent: {sorted(missing_spans)} "
                        f"(got {sorted(names)})")
    # device-wait attribution must land somewhere inside polish
    polish = [ev for ev in events if ev["name"].startswith("polish")]
    if polish and not any(ev["args"]["device_wait_ms"] > 0 for ev in polish):
        problems.append("no polish span carries device-wait attribution")
    return problems


def main() -> int:
    from pbccs_tpu import cli

    tmp = tempfile.mkdtemp(prefix="pbccs_obs_smoke_")
    fasta = os.path.join(tmp, "subreads.fasta")
    trace_path = os.path.join(tmp, "trace.json")
    make_workload(fasta)
    rc = cli.run([os.path.join(tmp, "out.fasta"), fasta,
                  "--skipChemistryCheck", "--zmws", "all",
                  "--reportFile", os.path.join(tmp, "report.csv"),
                  "--trace-out", trace_path])
    if rc != 0:
        print(f"obs_smoke: cli.run failed rc={rc}", file=sys.stderr)
        return 1
    with open(trace_path) as f:
        trace = json.load(f)
    problems = validate_trace(trace)
    if problems:
        for p in problems:
            print(f"obs_smoke: {p}", file=sys.stderr)
        return 1
    n = len(trace["traceEvents"])
    print(f"obs_smoke: OK ({n} spans, schema valid, "
          f"spans cover {sorted(REQUIRED_SPANS)})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
