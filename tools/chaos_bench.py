#!/usr/bin/env python
"""Chaos bench: the full resilience harness (superset of chaos_smoke).

Injects every fault class the resilience subsystem handles and asserts
the recovery contract, including the process-level legs the smoke test
skips:

  1. IN-PROCESS FAULT MATRIX -- poison ZMW (bisect + serial + degrade),
     transient device error, hung dispatch vs watchdog: surviving-ZMW
     outputs must be byte-identical to a fault-free run (chaos_smoke's
     checks, at bench scale).
  2. KILL -9 / RESUME -- a real `ccs` subprocess with --checkpoint is
     SIGKILLed after its first journaled chunk; rerunning with --resume
     must produce byte-identical output + yield report vs an
     uninterrupted run, restoring (not recomputing) the journaled
     chunks.
  3. CRASH / RESUME -- a workqueue-task fault (--faults
     workqueue.task:error@2*1) makes the run die with a nonzero exit;
     --resume completes it to the identical output.
  4. SERVE WATCHDOG -- a live engine with a short polish deadline fed a
     hung dispatch: the affected requests fail with a structured
     timeout, the engine keeps serving, and a follow-up request
     succeeds.
  5. OOM MATRIX (--ooms) -- injected device OOMs at the dispatch site:
     full output parity every round (never a quarantined healthy
     batch), governor ceilings recorded, later rounds pre-split at
     admission.
  6. INPUT FUZZ -- the randomized long leg of tools/fuzz_inputs.py:
     --fuzzRounds seeded structured corruptions over the BAM decode
     classes (bit flips, truncation, length-field lies, tag mutations),
     asserting the hardening invariant at bench scale (process
     survives, valid records byte-identical, rejections counted).

Reports JSON (stdout, plus --out FILE).

Usage:
    JAX_PLATFORMS=cpu python tools/chaos_bench.py --zmws 10
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, ".")  # runnable as tools/chaos_bench.py from the repo root

from pbccs_tpu.models.arrow.params import decode_bases
from pbccs_tpu.pipeline import Chunk, Failure, Subread, process_chunks
from pbccs_tpu.resilience import faults, watchdog
from pbccs_tpu.runtime.logging import Logger, LogLevel
from pbccs_tpu.simulate import simulate_zmw


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--zmws", type=int, default=10)
    p.add_argument("--tplLen", type=int, default=80)
    p.add_argument("--passes", type=int, default=5)
    p.add_argument("--chunkSize", type=int, default=2,
                   help="CLI work-item size (small: many journal records)")
    p.add_argument("--seed", type=int, default=20260803)
    p.add_argument("--skip-subprocess", action="store_true",
                   help="skip the kill -9 / crash CLI legs (fast mode)")
    p.add_argument("--fuzzRounds", type=int, default=40,
                   help="randomized input-fuzz rounds (0 disables)")
    p.add_argument("--ooms", type=int, default=3,
                   help="injected device-OOM rounds (governor split "
                        "parity + admission pre-split; 0 disables)")
    p.add_argument("--out", default=None, help="also write the JSON here")
    return p


def make_chunks(args) -> list[Chunk]:
    rng = np.random.default_rng(args.seed)
    out = []
    for i in range(args.zmws):
        _, reads, _, snr = simulate_zmw(rng, args.tplLen, args.passes)
        out.append(Chunk(
            f"bench/{i}",
            [Subread(f"bench/{i}/{k}", r) for k, r in enumerate(reads)],
            snr))
    return out


def write_fasta_workload(chunks: list[Chunk], path: str) -> None:
    with open(path, "w") as f:
        for c in chunks:
            movie, hole = c.id.split("/")
            for k, r in enumerate(c.reads):
                f.write(f">{movie}/{hole}/{k}_{k + 1}\n"
                        f"{decode_bases(r.seq)}\n")


def outputs(tally) -> dict[str, tuple[str, str]]:
    return {r.id: (r.sequence, r.qualities) for r in tally.results}


class CheckFailed(AssertionError):
    pass


def check(report: dict, name: str, ok: bool, detail: str = "") -> None:
    report[name] = bool(ok) if not detail else f"{bool(ok)} ({detail})"
    print(f"  {'PASS' if ok else 'FAIL'}  {name}"
          + (f"  ({detail})" if detail else ""))
    if not ok:
        raise CheckFailed(name)


# ------------------------------------------------------ 1. in-process matrix

def leg_fault_matrix(chunks, report: dict) -> None:
    print("== leg 1: in-process fault matrix ==")
    poison = chunks[len(chunks) // 2].id
    base = process_chunks(list(chunks))
    base_out = outputs(base)
    survivors = {k: v for k, v in base_out.items() if k != poison}
    report["baseline_successes"] = base.counts[Failure.SUCCESS]

    with faults.active(f"polish.dispatch:error~{poison}"):
        pois = process_chunks(list(chunks))
    check(report, "bisect_survivor_parity", outputs(pois) == survivors)
    check(report, "bisect_quarantined",
          pois.counts[Failure.OTHER] == 1)

    with faults.active(f"polish.dispatch:error~{poison}"):
        ser = process_chunks(list(chunks), on_error="serial")
    check(report, "serial_survivor_parity", outputs(ser) == survivors)

    with faults.active("polish.dispatch:error=transient@1*1"):
        tr = process_chunks(list(chunks))
    check(report, "transient_full_parity", outputs(tr) == base_out)

    # deadline well above a legitimate re-dispatch, hang longer than the
    # process lifetime (the abandoned thread stays in time.sleep, never
    # re-entering XLA at interpreter teardown)
    watchdog.configure(20.0)
    try:
        with faults.active("polish.dispatch:delay=3600@1*1"):
            hung = process_chunks(list(chunks))
    finally:
        watchdog.configure(None)
    check(report, "watchdog_recovery_parity", outputs(hung) == base_out)


# ------------------------------------------------------- 2. kill -9 / resume

def _cli_cmd(out_path, fasta, args, extra=()):
    return [sys.executable, "-m", "pbccs_tpu.cli", "--skipChemistryCheck",
            "--chunkSize", str(args.chunkSize),
            "--reportFile", out_path + ".csv",
            *extra, out_path, fasta]


def _run_cli(cmd, timeout=900):
    return subprocess.run(cmd, env=dict(os.environ, JAX_PLATFORMS="cpu"),
                          capture_output=True, text=True, timeout=timeout)


def _journal_chunks(path: str) -> int:
    if not os.path.exists(path):
        return 0
    n = 0
    with open(path) as f:
        for line in f:
            try:
                n += json.loads(line).get("type") == "chunk"
            except ValueError:
                pass
    return n


def leg_kill9_resume(args, tmp, fasta, report: dict) -> None:
    print("== leg 2: kill -9 mid-run, then --resume ==")
    ref = os.path.join(tmp, "ref.fasta")
    r = _run_cli(_cli_cmd(ref, fasta, args))
    check(report, "uninterrupted_run_ok", r.returncode == 0,
          r.stderr[-300:] if r.returncode else "")

    out = os.path.join(tmp, "killed.fasta")
    ckpt = os.path.join(tmp, "killed.ckpt")
    proc = subprocess.Popen(
        _cli_cmd(out, fasta, args, ("--checkpoint", ckpt)),
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    # wait for the first journaled chunk, then kill -9 (no cleanup runs)
    deadline = time.monotonic() + 600
    while time.monotonic() < deadline and proc.poll() is None:
        if _journal_chunks(ckpt) >= 1:
            break
        time.sleep(0.2)
    journaled = _journal_chunks(ckpt)
    if proc.poll() is None:
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(30)
    check(report, "killed_with_journaled_chunks", journaled >= 1,
          f"{journaled} chunk(s) journaled before SIGKILL")
    check(report, "kill_was_mid_run", proc.returncode != 0,
          f"exit {proc.returncode}")

    r = _run_cli(_cli_cmd(out, fasta, args,
                          ("--checkpoint", ckpt, "--resume")))
    check(report, "resume_run_ok", r.returncode == 0,
          r.stderr[-300:] if r.returncode else "")
    check(report, "resume_restored_chunks",
          f"restored {journaled} completed chunk" in r.stderr
          or journaled == 0, f"journal had {journaled}")
    check(report, "resume_output_identical",
          open(ref).read() == open(out).read())
    check(report, "resume_report_identical",
          open(ref + ".csv").read() == open(out + ".csv").read())
    check(report, "journal_removed_after_success",
          not os.path.exists(ckpt))


def leg_crash_resume(args, tmp, fasta, report: dict) -> None:
    print("== leg 3: worker-task crash, then --resume ==")
    ref = os.path.join(tmp, "ref.fasta")   # from leg 2
    out = os.path.join(tmp, "crashed.fasta")
    ckpt = os.path.join(tmp, "crashed.ckpt")
    r = _run_cli(_cli_cmd(out, fasta, args,
                          ("--checkpoint", ckpt,
                           "--faults", "workqueue.task:error@2*1")))
    check(report, "crash_exit_nonzero", r.returncode != 0,
          f"exit {r.returncode}")
    check(report, "crash_left_journal", os.path.exists(ckpt))
    r = _run_cli(_cli_cmd(out, fasta, args,
                          ("--checkpoint", ckpt, "--resume")))
    check(report, "crash_resume_ok", r.returncode == 0,
          r.stderr[-300:] if r.returncode else "")
    check(report, "crash_resume_output_identical",
          open(ref).read() == open(out).read())
    check(report, "crash_resume_report_identical",
          open(ref + ".csv").read() == open(out + ".csv").read())


# --------------------------------------------------------- 4. serve watchdog

def leg_serve_watchdog(chunks, report: dict) -> None:
    """Engine-level watchdog semantics (stubbed pipeline: the engine's
    behavior is under test here; the REAL pipeline's hang recovery is
    leg 1's watchdog_recovery_parity).  A polish deadline short enough
    to catch the injected 30 s hang would also catch a legitimate
    cold-compile CPU polish, so the stub keeps the leg deterministic."""
    print("== leg 4: serve engine watchdog ==")
    from pbccs_tpu.pipeline import PreparedZmw
    from pbccs_tpu.serve.engine import CcsEngine, ServeConfig

    def stub_prep(chunk, settings):
        return None, PreparedZmw(chunk, np.zeros(64, np.int8), [],
                                 len(chunk.reads), 0, 0.0)

    def stub_polish(preps, settings):
        # the injected delay=30@1 hangs the FIRST dispatch only
        faults.maybe_fail("polish.dispatch",
                          keys=[p.chunk.id for p in preps])
        from pbccs_tpu.pipeline import Failure as F
        return [(F.SUCCESS, None) for _ in preps]

    cfg = ServeConfig(max_batch=2, max_wait_ms=100.0,
                      polish_timeout_ms=1500.0)
    with faults.active("polish.dispatch:delay=30@1"):
        with CcsEngine(config=cfg, prep_fn=stub_prep,
                       polish_fn=stub_polish) as eng:
            hung = [eng.submit(c) for c in chunks[:2]]
            for h in hung:
                check(report, f"hung_request_completed_{h.chunk.id}",
                      h.wait(60.0))
            check(report, "hung_requests_failed_structured",
                  all(h.error is not None and "watchdog" in h.error
                      for h in hung))
            # the SAME engine keeps serving: the delay spec fired on @1
            # only, so the follow-up polish completes normally
            ok = eng.submit(chunks[2])
            check(report, "engine_serves_after_timeout",
                  ok.wait(60.0) and ok.error is None)
            check(report, "engine_status_alive",
                  eng.status()["engine"] == "ccs-serve")


# ------------------------------------------------- 5. OOM-adaptive dispatch

def leg_oom_matrix(chunks, args, report: dict) -> None:
    """--ooms rounds of injected device OOMs at the dispatch site: every
    round must complete with FULL output parity (a capacity failure
    costs wall time, never results, and never quarantines a healthy
    batch), the memory governor must record a shape ceiling, and later
    rounds must pre-split at admission instead of re-discovering the
    OOM."""
    print(f"== leg 5: OOM-adaptive dispatch ({args.ooms} rounds) ==")
    from pbccs_tpu.obs.metrics import default_registry
    from pbccs_tpu.resilience import resources

    base = process_chunks(list(chunks))
    base_out = outputs(base)
    reg = default_registry()
    for rnd in range(args.ooms):
        scope = reg.scope()
        with faults.active("polish.dispatch:oom@1*1", seed=rnd):
            oomed = process_chunks(list(chunks))
        check(report, f"oom_round{rnd}_full_parity",
              outputs(oomed) == base_out)
        check(report, f"oom_round{rnd}_never_quarantines",
              scope.counter_value("ccs_quarantined_zmws_total") == 0)
        if rnd == 0:
            check(report, "oom_split_redispatch",
                  scope.counter_value(
                      "ccs_resource_oom_splits_total") >= 1)
        else:
            check(report, f"oom_round{rnd}_admission_presplit",
                  scope.counter_value(
                      "ccs_resource_presplit_batches_total") >= 1)
    check(report, "oom_governor_ceiling_recorded",
          bool(resources.default_governor().snapshot()))


# ---------------------------------------------------------- 6. input fuzz

def leg_input_fuzz(args, report: dict) -> None:
    """The randomized long leg of the structured input fuzzer: every
    decode corruption class re-rolled --fuzzRounds times (fuzz_inputs
    --smoke is the deterministic tier-1 subset of this)."""
    print(f"== leg 6: randomized input fuzz ({args.fuzzRounds} rounds) ==")
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import fuzz_inputs

    rc = fuzz_inputs.main(["--seed", str(args.seed),
                           "--rounds", str(args.fuzzRounds)])
    check(report, "input_fuzz_rounds", rc == 0,
          f"{args.fuzzRounds} rounds, seed {args.seed}")


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from pbccs_tpu.runtime.cache import enable_compilation_cache

    enable_compilation_cache()
    Logger.default(Logger(level=LogLevel.ERROR))
    report: dict = {"workload": vars(args).copy()}
    chunks = make_chunks(args)
    tmp = tempfile.mkdtemp(prefix="chaos_bench_")
    fasta = os.path.join(tmp, "workload.fasta")
    write_fasta_workload(chunks, fasta)

    failed = False
    try:
        leg_fault_matrix(chunks, report)
        if not args.skip_subprocess:
            leg_kill9_resume(args, tmp, fasta, report)
            leg_crash_resume(args, tmp, fasta, report)
        leg_serve_watchdog(chunks, report)
        if args.ooms:
            leg_oom_matrix(chunks, args, report)
        if args.fuzzRounds:
            leg_input_fuzz(args, report)
    except CheckFailed as e:
        report["failed"] = str(e)
        failed = True

    out = json.dumps(report, indent=2, default=str)
    print(out)
    if args.out:
        with open(args.out, "w") as f:
            f.write(out + "\n")
    print("chaos bench:", "FAILED" if failed else "all checks passed")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
