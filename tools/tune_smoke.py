#!/usr/bin/env python
"""`ccs tune` smoke for the tier-1 gate: the autotuner's four promises,
end to end on a tiny CPU workload.

Runs ONE real `ccs tune` search (fresh subprocesses per candidate, the
production driver, no mocks) over a deliberately loaded two-candidate
band-width grid -- `band_w=16` is empirically output-CHANGING on this
workload, `band_w=48` is byte-identical and less work than the default
64 -- then asserts:

  1. REJECTION: the output-changing candidate is rejected and REPORTED
     (`output differs from defaults`), never ranked -- the
     byte-identity rule is the autotuner's safety contract;
  2. SHIP: a profile is emitted (`--minGain -1` smoke mode +
     `--set router_spill_depth=4`, so a ship never depends on CPU
     timing luck), schema-versioned, fingerprinted for THIS host, and
     referee-clean (perf_gate violations empty, band_w's declared
     compile-count exemptions noted);
  3. LOADER: runtime.tuning applies the emitted profile in-process
     (knobs resolve, `ledger_tag` == profile id) and a fingerprint
     mutation makes it fall through to defaults with a note;
  4. END TO END: a fresh batch CLI run under `--tuneProfile` produces
     output byte-identical to the tune search's defaults run and
     stamps `tuned_profile=<id>` into its perf-ledger records.

The emitted profile is copied to $ARTIFACTS_DIR (default
/tmp/ccs-tune-artifacts) for CI upload.

Usage:  JAX_PLATFORMS=cpu python tools/tune_smoke.py
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

N_ZMWS = 8
TPL_LEN = 120
N_PASSES = 3
CHUNK = 8
BAD_BAND_W = 16    # empirically changes consensus bytes on this workload
GOOD_BAND_W = 48   # byte-identical, narrower than the default 64


def fail(msg: str) -> None:
    print(f"tune_smoke: FAIL: {msg}")
    sys.exit(1)


def sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def run_tune(workdir: str, out_path: str) -> dict:
    cmd = [sys.executable, "-m", "pbccs_tpu.cli", "tune",
           "--out", out_path, "--workdir", workdir,
           "--zmws", str(N_ZMWS), "--passes", str(N_PASSES),
           "--tplLen", str(TPL_LEN), "--chunkSize", str(CHUNK),
           "--repeat", "1", "--devices", "1",
           "--knobs", "band_w",
           "--candidates", f"band_w={BAD_BAND_W},{GOOD_BAND_W}",
           "--set", "router_spill_depth=4",
           "--minGain", "-1", "--logLevel", "WARN"]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PBCCS_TUNE_PROFILE", None)
    proc = subprocess.run(cmd, env=env, cwd=REPO,
                          capture_output=True, text=True)
    if proc.returncode != 0:
        fail(f"ccs tune exited {proc.returncode}:\n"
             f"{proc.stderr[-1500:]}\n{proc.stdout[-500:]}")
    try:
        return json.loads(proc.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        fail(f"ccs tune printed no JSON summary line: {proc.stdout!r}")
        raise  # unreachable; keeps type-checkers quiet


def defaults_digest(workdir: str) -> str:
    """The tune search's own record of the defaults-run output digest,
    read back from its resume journal."""
    from pbccs_tpu.obs.ledger import read_ledger
    from pbccs_tpu.tune.driver import assignment_key

    records, _ = read_ledger(os.path.join(workdir, "journal.ndjson"))
    for rec in records:
        if rec.get("tune_journal") == 1 and rec.get("assignment") == {}:
            return rec.get("digest") or ""
    fail("tune journal carries no defaults-run digest")
    raise AssertionError  # unreachable


def check_loader(out_path: str, summary: dict) -> None:
    from pbccs_tpu.runtime import tuning
    from pbccs_tpu.tune.profile import load_profile, save_profile

    prof, note = load_profile(out_path)
    if prof is None:
        fail(f"emitted profile does not load: {note}")
    if prof.profile_id != summary.get("profile_id"):
        fail(f"profile id drift: file {prof.profile_id} vs summary "
             f"{summary.get('profile_id')}")

    tuning.reset()
    if not tuning.configure(out_path):
        fail("tuning.configure refused the emitted profile on the "
             "host that produced it")
    if tuning.knob_int("router_spill_depth") != 4:
        fail("forced knob router_spill_depth did not resolve from the "
             "applied profile")
    if tuning.ledger_tag() != prof.profile_id:
        fail(f"ledger_tag {tuning.ledger_tag()!r} != applied profile "
             f"id {prof.profile_id}")
    print(f"tune_smoke: loader applied profile {prof.profile_id} "
          f"(knobs {sorted(prof.knobs)})")

    # fingerprint mismatch must fall through to defaults, not crash
    import dataclasses

    alien = dataclasses.replace(
        prof, fingerprint=dict(prof.fingerprint, jax_version="0.0.0"))
    alien_path = out_path + ".alien"
    save_profile(alien, alien_path)
    tuning.reset()
    if tuning.configure(alien_path):
        fail("a fingerprint-mismatched profile was applied")
    if tuning.knob_int("router_spill_depth") is not None:
        fail("knobs leaked through a rejected profile")
    tuning.reset()
    print("tune_smoke: fingerprint mismatch falls through to defaults")


def check_end_to_end(workdir: str, out_path: str, summary: dict) -> None:
    """A fresh batch CLI run under --tuneProfile: byte-identical output
    to the tune search's defaults run, tuned_profile stamped in the
    ledger."""
    from pbccs_tpu.obs.ledger import read_ledger

    calib = os.path.join(workdir, "calibration.fasta")
    out = os.path.join(workdir, "tuned_run.fasta")
    ledger = os.path.join(workdir, "tuned_run.ndjson")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmd = [sys.executable, "-m", "pbccs_tpu.cli", out, calib,
           "--skipChemistryCheck", "--devices", "1",
           "--chunkSize", str(CHUNK), "--perfLedger", ledger,
           "--reportFile", os.path.join(workdir, "tuned_run_report.csv"),
           "--tuneProfile", out_path, "--logLevel", "WARN"]
    proc = subprocess.run(cmd, env=env, cwd=REPO,
                          capture_output=True, text=True)
    if proc.returncode != 0:
        fail(f"--tuneProfile run exited {proc.returncode}: "
             f"{proc.stderr[-1000:]}")
    want = defaults_digest(workdir)
    got = sha256(out)
    if got != want:
        fail(f"tuned run output digest {got[:12]} != defaults "
             f"{want[:12]} -- the shipped profile changed the answer")
    records, _ = read_ledger(ledger)
    runs = [r for r in records if r.get("kind") == "batch_run"]
    if not runs:
        fail("tuned run produced no batch_run ledger record")
    tags = {r.get("tuned_profile") for r in runs}
    if tags != {summary["profile_id"]}:
        fail(f"ledger tuned_profile {tags} != shipped profile id "
             f"{summary['profile_id']}")
    print("tune_smoke: --tuneProfile run byte-identical to defaults, "
          f"ledger stamped tuned_profile={summary['profile_id']}")


def main() -> None:
    t0 = time.monotonic()
    workdir = tempfile.mkdtemp(prefix="ccs_tune_smoke_")
    out_path = os.path.join(workdir, "profile.json")

    summary = run_tune(workdir, out_path)

    # 1. the output-changing candidate is rejected + reported
    bad = [r for r in summary.get("rejected", [])
           if r.get("assignment") == {"band_w": BAD_BAND_W}]
    if not bad:
        fail(f"band_w={BAD_BAND_W} was not rejected: "
             f"{json.dumps(summary)[:800]}")
    if "output differs from defaults" not in bad[0].get("reason", ""):
        fail(f"wrong rejection reason: {bad[0]}")
    print(f"tune_smoke: band_w={BAD_BAND_W} rejected "
          f"({bad[0]['reason']})")

    # 2. a profile shipped, referee-clean
    if not summary.get("shipped"):
        fail(f"no profile shipped: {json.dumps(summary)[:800]}")
    if summary["referee"]["violations"]:
        fail(f"referee violations on the shipped winner: "
             f"{summary['referee']['violations']}")
    if not os.path.exists(out_path):
        fail(f"summary says shipped but {out_path} does not exist")
    win = summary["winner"]["assignment"]
    print(f"tune_smoke: shipped {summary['profile_id']} "
          f"(winner {win or 'defaults'}, gain "
          f"{summary['winner']['gain']:+.2%}, referee clean)")
    if win.get("band_w") == BAD_BAND_W:
        fail("the output-changing candidate won the search")

    # 3. loader ladder
    check_loader(out_path, summary)

    # 4. end-to-end apply + attribution
    check_end_to_end(workdir, out_path, summary)

    art_dir = os.environ.get("ARTIFACTS_DIR", "/tmp/ccs-tune-artifacts")
    os.makedirs(art_dir, exist_ok=True)
    shutil.copy(out_path, os.path.join(art_dir, "tune_profile.json"))
    print(f"tune_smoke: profile artifact -> "
          f"{os.path.join(art_dir, 'tune_profile.json')}")
    print(f"tune_smoke: PASS in {time.monotonic() - t0:.1f}s")


if __name__ == "__main__":
    main()
