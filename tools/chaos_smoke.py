#!/usr/bin/env python
"""Chaos smoke for the tier-1 gate: one fault per class on a simulated
dataset, asserting the resilience contract end to end.

Fault classes (pbccs_tpu/resilience/):

  poison    a ZMW whose polish always raises -> quarantine bisection
            isolates it; SURVIVING ZMWs are byte-identical to the
            fault-free run; quarantine metrics move
  degrade   the same poison with --degradeQuarantined semantics -> the
            poison ZMW emits a draft-only consensus (capped QVs)
  transient a one-shot retryable device error -> RetryPolicy absorbs
            it; ALL outputs identical to fault-free
  hang      a dispatch that sleeps past the watchdog deadline ->
            structured WatchdogTimeout, bisection recovers every ZMW
  serial    the legacy whole-batch serial fallback path: same
            surviving-output parity as bisection
  serve     a live engine fed the poison ZMW keeps serving; surviving
            replies match the offline run

Runs on CPU in-process (compiled programs are shared across checks), so
it is cheap enough for CI: tools/tier1.sh runs it after obs_smoke.

Usage:  JAX_PLATFORMS=cpu python tools/chaos_smoke.py
"""

from __future__ import annotations

import sys

import numpy as np

sys.path.insert(0, ".")  # runnable as tools/chaos_smoke.py from the repo root

from pbccs_tpu.obs.metrics import default_registry
from pbccs_tpu.pipeline import (
    Chunk,
    ConsensusSettings,
    Failure,
    Subread,
    process_chunks,
)
from pbccs_tpu.resilience import faults, watchdog
from pbccs_tpu.runtime.logging import Logger, LogLevel
from pbccs_tpu.simulate import simulate_zmw

N_ZMWS = 6
POISON = "smoke/2"


def make_workload() -> list[Chunk]:
    rng = np.random.default_rng(20260803)
    chunks = []
    for i in range(N_ZMWS):
        _, reads, _, snr = simulate_zmw(rng, 60, 5)
        chunks.append(Chunk(
            f"smoke/{i}",
            [Subread(f"smoke/{i}/{k}", r) for k, r in enumerate(reads)],
            snr))
    return chunks


def outputs(tally) -> dict[str, tuple[str, str]]:
    return {r.id: (r.sequence, r.qualities) for r in tally.results}


def check(name: str, ok: bool, detail: str = "") -> None:
    print(f"  {'PASS' if ok else 'FAIL'}  {name}" +
          (f"  ({detail})" if detail else ""))
    if not ok:
        raise SystemExit(f"chaos smoke failed: {name} {detail}")


def main() -> int:
    from pbccs_tpu.runtime.cache import enable_compilation_cache

    enable_compilation_cache()
    Logger.default(Logger(level=LogLevel.ERROR))
    reg = default_registry()
    chunks = make_workload()

    print("== baseline (fault-free) ==")
    base = process_chunks(list(chunks))
    base_out = outputs(base)
    check("baseline yields successes", base.counts[Failure.SUCCESS] >= 4,
          f"{base.counts[Failure.SUCCESS]}/{N_ZMWS}")
    survivors = {k: v for k, v in base_out.items() if k != POISON}

    print("== poison ZMW -> quarantine bisection ==")
    scope = reg.scope()
    with faults.active(f"polish.dispatch:error~{POISON}"):
        pois = process_chunks(list(chunks))
    check("run completed", pois.total == base.total)
    check("poison ZMW quarantined as Other",
          pois.counts[Failure.OTHER] == 1)
    check("surviving outputs byte-identical", outputs(pois) == survivors)
    check("ccs_quarantined_zmws_total moved",
          scope.counter_value("ccs_quarantined_zmws_total") == 1)
    check("ccs_faults_injected_total moved",
          scope.counter_value("ccs_faults_injected_total",
                              site="polish.dispatch", kind="error") > 0)

    print("== poison ZMW -> draft-only degradation ==")
    scope = reg.scope()
    with faults.active(f"polish.dispatch:error~{POISON}"):
        deg = process_chunks(list(chunks),
                             ConsensusSettings(degrade_quarantined=True))
    drafts = [r for r in deg.results if r.draft_only]
    check("poison ZMW emitted as draft-only",
          [r.id for r in drafts] == [POISON])
    check("draft QVs capped", all(
        q <= 10 for r in drafts for q in r.qvs))
    check("non-degraded outputs byte-identical",
          {k: v for k, v in outputs(deg).items() if k != POISON}
          == survivors)
    check("ccs_degraded_zmws_total moved",
          scope.counter_value("ccs_degraded_zmws_total") == 1)

    print("== transient device error -> retry ==")
    scope = reg.scope()
    with faults.active("polish.dispatch:error=transient@1*1"):
        tr = process_chunks(list(chunks))
    check("all outputs identical after retry", outputs(tr) == base_out)
    check("ccs_retries_total moved",
          scope.counter_value("ccs_retries_total",
                              site="polish.dispatch") >= 1)

    print("== device OOM -> governor split (never quarantine) ==")
    scope = reg.scope()
    with faults.active("polish.dispatch:oom@1*1"):
        oomed = process_chunks(list(chunks))
    check("all outputs identical after OOM split",
          outputs(oomed) == base_out)
    check("no ZMW quarantined by the OOM",
          scope.counter_value("ccs_quarantined_zmws_total") == 0)
    check("ccs_resource_oom_splits_total moved",
          scope.counter_value("ccs_resource_oom_splits_total") >= 1)
    check("governor recorded a shape ceiling",
          scope.counter_value("ccs_resource_oom_ceilings_total") >= 1)
    check("no same-shape retry of the OOM",
          scope.counter_value("ccs_retries_total",
                              site="polish.dispatch") == 0)

    print("== hung dispatch -> watchdog + bisection recovery ==")
    scope = reg.scope()
    # size the deadline as an operator would: well above a legitimate
    # re-dispatch (seconds on CPU), well below the injected hang.  The
    # hang outlives the process so its abandoned thread is still inside
    # time.sleep -- never inside XLA -- at interpreter teardown.
    watchdog.configure(20.0)
    try:
        with faults.active("polish.dispatch:delay=3600@1*1"):
            hung = process_chunks(list(chunks))
    finally:
        watchdog.configure(None)
    check("all outputs identical after watchdog recovery",
          outputs(hung) == base_out)
    check("ccs_watchdog_timeouts_total moved",
          scope.counter_value("ccs_watchdog_timeouts_total",
                              site="polish.dispatch") >= 1)

    print("== poison ZMW -> legacy serial fallback ==")
    with faults.active(f"polish.dispatch:error~{POISON}"):
        ser = process_chunks(list(chunks), on_error="serial")
    check("serial path surviving outputs byte-identical",
          outputs(ser) == survivors)
    check("serial path quarantined the poison ZMW",
          ser.counts[Failure.OTHER] == 1)

    print("== live serve engine survives the poison ==")
    from pbccs_tpu.serve.engine import CcsEngine, ServeConfig

    with faults.active(f"polish.dispatch:error~{POISON}"):
        with CcsEngine(config=ServeConfig(max_batch=N_ZMWS,
                                          max_wait_ms=60_000.0)) as eng:
            reqs = [eng.submit(c) for c in chunks]
            for r in reqs:
                check(f"reply for {r.chunk.id}", r.wait(600.0))
            served = {r.chunk.id: (r.result.sequence, r.result.qualities)
                      for r in reqs if r.failure == Failure.SUCCESS}
            check("served survivors match offline", served == survivors)
            check("engine still answers status",
                  eng.status()["engine"] == "ccs-serve")

    print("chaos smoke: all checks passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
