#!/usr/bin/env python
"""Perf-ledger smoke for the tier-1 gate: determinism + sentinel wiring.

Runs ONE small fixed workload TWICE through the batch CLI (fresh
subprocess + fresh compile cache each time, so the two runs are
byte-equivalent experiments), then asserts the three properties the
performance-observability layer is trusted for:

  1. SCHEMA: every ledger record is schema-versioned and every field it
     carries is declared in obs.ledger.LEDGER_FIELDS (the REG011
     drift-checked schema);
  2. DETERMINISM: the CPU-deterministic classes (counter / ratio /
     compile) are IDENTICAL across the two runs -- the property that
     makes enforcing them everywhere honest;
  3. SENTINEL: tools/perf_gate.py passes the fresh ledger against the
     committed PERF_BASELINE.json in --counters-only mode, and a
     deliberately perturbed ledger (counter bump + padding-waste shift)
     makes it exit nonzero with a structured diff naming the metric.

The fresh ledger is copied to $ARTIFACTS_DIR (default
/tmp/ccs-perf-artifacts) for CI upload.

Usage:  JAX_PLATFORMS=cpu python tools/perf_smoke.py
        ... --update-baseline   # regenerate PERF_BASELINE.json from
                                # run 1 (prints every accepted delta)
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_ZMWS = 8
TPL_LEN = 120
N_PASSES = 5
CHUNK = 4
SEED = 20260804

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE = os.path.join(REPO, "PERF_BASELINE.json")


def _child_env(cache_dir: str) -> dict:
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               JAX_COMPILATION_CACHE_DIR=cache_dir,
               # host refinement loop: sane CPU compile budget, and the
               # ledger's refine_rounds_host counter gets real rounds
               PBCCS_DEVICE_REFINE="0")
    return env


def write_workload(path: str) -> None:
    import numpy as np

    from bench import build_tasks
    from pbccs_tpu.models.arrow.params import decode_bases

    tasks, _ = build_tasks(np.random.default_rng(SEED), N_ZMWS, TPL_LEN,
                           str(N_PASSES), 1)
    with open(path, "w") as f:
        for t in tasks:
            z = t.id.split("/")[1]
            start = 0
            for read in t.reads:
                seq = decode_bases(read)
                f.write(f">perf/{z}/{start}_{start + len(seq)}\n{seq}\n")
                start += len(seq) + 50


def run_once(tmp: str, fasta: str, tag: str) -> str:
    """One fresh `ccs` subprocess writing its own ledger; returns the
    ledger path."""
    cache = os.path.join(tmp, f"cache_{tag}")
    ledger = os.path.join(tmp, f"ledger_{tag}.ndjson")
    out = os.path.join(tmp, f"out_{tag}.bam")
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, "-m", "pbccs_tpu.cli", out, fasta,
         "--skipChemistryCheck", "--chunkSize", str(CHUNK),
         "--numThreads", "2", "--zmws", "all",
         "--reportFile", os.path.join(tmp, f"report_{tag}.csv"),
         "--perfLedger", ledger, "--logLevel", "WARN"],
        env=_child_env(cache), capture_output=True, text=True,
        timeout=480)
    dt = time.monotonic() - t0
    if proc.returncode != 0:
        raise AssertionError(
            f"run {tag} failed rc={proc.returncode}:\n"
            f"{proc.stderr[-2000:]}")
    print(f"perf_smoke: run {tag} OK in {dt:.1f}s")
    return ledger


def load_single_record(ledger: str) -> dict:
    from pbccs_tpu.obs.ledger import read_ledger

    records, skipped = read_ledger(ledger)
    assert skipped == 0, f"{ledger}: {skipped} unparseable line(s)"
    runs = [r for r in records if r.get("kind") == "batch_run"]
    assert len(runs) == 1, \
        f"{ledger}: want exactly 1 batch_run record, got {len(runs)}"
    return runs[0]


def assert_schema(rec: dict, ledger: str) -> None:
    from pbccs_tpu.obs.ledger import LEDGER_FIELDS, LEDGER_SCHEMA_VERSION

    assert rec.get("schema_version") == LEDGER_SCHEMA_VERSION, rec
    alien = sorted(set(rec) - set(LEDGER_FIELDS))
    assert not alien, f"{ledger}: fields outside LEDGER_FIELDS: {alien}"
    for required in ("kind", "t_unix", "source", "zmws", "results",
                     "polish_dispatches", "refine_rounds_host",
                     "zmw_slots", "peak_rss_bytes", "wall_s"):
        assert required in rec, f"{ledger}: missing field {required}"
    print(f"perf_smoke: schema OK ({len(rec)} fields)")


def assert_deterministic(rec1: dict, rec2: dict) -> None:
    from pbccs_tpu.obs.ledger import LEDGER_FIELDS

    gated = {f for f, c in LEDGER_FIELDS.items()
             if c in ("counter", "ratio", "compile")}
    diffs = []
    for field in sorted(gated):
        if rec1.get(field) != rec2.get(field):
            diffs.append(f"{field}: {rec1.get(field)!r} != "
                         f"{rec2.get(field)!r}")
    assert not diffs, ("CPU-deterministic ledger counters drifted "
                       "between two identical runs:\n  "
                       + "\n  ".join(diffs))
    n = sum(1 for f in gated if f in rec1)
    print(f"perf_smoke: determinism OK ({n} gated fields identical "
          "across runs)")


def run_gate(argv: list[str]) -> tuple[int, str]:
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "perf_gate.py")]
        + argv,
        capture_output=True, text=True, timeout=120)
    return proc.returncode, proc.stdout + proc.stderr


def main() -> int:
    # the parent only SIMULATES the workload (numpy + task dataclasses),
    # but the import chain touches jax -- pin it to CPU when unset
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    update = "--update-baseline" in sys.argv[1:]
    t0 = time.monotonic()
    tmp = tempfile.mkdtemp(prefix="pbccs_perf_smoke_")
    try:
        fasta = os.path.join(tmp, "perf_smoke.fasta")
        write_workload(fasta)
        ledger1 = run_once(tmp, fasta, "a")
        rec1 = load_single_record(ledger1)
        assert_schema(rec1, ledger1)

        if update:
            rc, out = run_gate([ledger1, "--update-baseline",
                                "--baseline", BASELINE])
            print(out, end="")
            return rc

        ledger2 = run_once(tmp, fasta, "b")
        rec2 = load_single_record(ledger2)
        assert_schema(rec2, ledger2)
        assert_deterministic(rec1, rec2)

        # the sentinel itself, in tier-1's counters-only mode
        rc, out = run_gate([ledger1, "--counters-only",
                            "--baseline", BASELINE])
        assert rc == 0, f"perf_gate failed on a clean ledger:\n{out}"
        print("perf_smoke: perf_gate OK vs committed PERF_BASELINE.json")

        # a perturbed ledger MUST fail with a structured diff: a
        # counter bump (always enforced) + a padding-waste shift
        perturbed = dict(rec1)
        perturbed["refine_rounds_host"] = \
            int(perturbed.get("refine_rounds_host", 0)) + 7
        perturbed["padding_waste"] = round(
            float(perturbed.get("padding_waste", 0.0)) + 0.25, 4)
        bad = os.path.join(tmp, "perturbed.ndjson")
        with open(bad, "w") as f:
            f.write(json.dumps(perturbed) + "\n")
        rc, out = run_gate([bad, "--counters-only",
                            "--baseline", BASELINE])
        assert rc == 1, f"perf_gate must fail a perturbed ledger: {out}"
        assert "refine_rounds_host" in out and "padding_waste" in out, \
            f"structured diff must name the perturbed metrics:\n{out}"
        assert "perf_gate_violation" in out, out
        print("perf_smoke: perturbed ledger correctly rejected with a "
              "structured diff")

        art_dir = os.environ.get("ARTIFACTS_DIR",
                                 "/tmp/ccs-perf-artifacts")
        os.makedirs(art_dir, exist_ok=True)
        shutil.copy(ledger1, os.path.join(art_dir, "perf_ledger.ndjson"))
        print(f"perf_smoke: ledger artifact -> "
              f"{os.path.join(art_dir, 'perf_ledger.ndjson')}")
        print(f"perf_smoke: PASS in {time.monotonic() - t0:.1f}s")
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
