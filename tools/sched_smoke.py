#!/usr/bin/env python
"""Scheduler smoke for the tier-1 gate: the device-fleet scheduler on 8
virtual CPU devices, asserting the dispatch contract end to end.

Legs:

  scaling   16 ZMWs in 4 chunk-batches through ScheduledPipeline over an
            8-device pool: output byte-identical to the single-device
            process_chunks driver, work actually spread over >= 2
            devices, sticky-routing metrics move
  chaos     a fault spec sickens ONE device (sched.dispatch keyed by the
            worker name, the faults.py registry): the run completes with
            ZERO lost ZMWs (outputs still byte-identical), the device is
            benched, requeues are counted
  serve     a live engine in fleet mode (ServeConfig.devices=0) with the
            same sick device: every request completes successfully, the
            engine stays up and reports the per-device breakdown

Runs on CPU in-process.  The 8-device platform must be forced BEFORE jax
initializes (same dance as tests/conftest.py), so run this as its own
process:  JAX_PLATFORMS=cpu python tools/sched_smoke.py
"""

from __future__ import annotations

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
# the host refinement loop keeps the compile budget sane on CPU (the
# device-resident loop is parity-pinned against it in test_device_refine)
os.environ.setdefault("PBCCS_DEVICE_REFINE", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

sys.path.insert(0, ".")  # runnable as tools/sched_smoke.py from the repo root

from pbccs_tpu.obs.metrics import default_registry  # noqa: E402
from pbccs_tpu.pipeline import (  # noqa: E402
    Chunk,
    ConsensusSettings,
    Failure,
    Subread,
    process_chunks,
)
from pbccs_tpu.resilience import faults  # noqa: E402
from pbccs_tpu.runtime.logging import Logger, LogLevel  # noqa: E402
from pbccs_tpu.sched import (  # noqa: E402
    DevicePool,
    DevicePoolConfig,
    ScheduledPipeline,
)
from pbccs_tpu.simulate import simulate_zmw  # noqa: E402

N_ZMWS = 16
BATCH = 4


def make_workload() -> list[list[Chunk]]:
    rng = np.random.default_rng(20260803)
    chunks = []
    for i in range(N_ZMWS):
        _, reads, _, snr = simulate_zmw(rng, 60, 5)
        chunks.append(Chunk(
            f"smoke/{i}",
            [Subread(f"smoke/{i}/{k}", r) for k, r in enumerate(reads)],
            snr))
    return [chunks[i: i + BATCH] for i in range(0, N_ZMWS, BATCH)]


def outputs(tallies) -> dict[str, tuple[str, str]]:
    return {r.id: (r.sequence, r.qualities)
            for t in tallies for r in t.results}


def total(tallies) -> int:
    return sum(t.total for t in tallies)


def check(name: str, ok: bool, detail: str = "") -> None:
    print(f"  {'PASS' if ok else 'FAIL'}  {name}" +
          (f"  ({detail})" if detail else ""))
    if not ok:
        raise SystemExit(f"sched smoke failed: {name} {detail}")


def run_scheduled(batches, settings, pool) -> list:
    pipe = ScheduledPipeline(pool, settings, prepare_workers=2)
    emitted = list(pipe.run(
        (i, list(b), None) for i, b in enumerate(batches)))
    check("emission order == submission order",
          [i for i, _ in emitted] == list(range(len(batches))))
    return [t for _, t in emitted]


def main() -> int:
    from pbccs_tpu.runtime.cache import enable_compilation_cache

    enable_compilation_cache()
    Logger.default(Logger(level=LogLevel.ERROR))
    reg = default_registry()
    devices = jax.devices()
    check("8 virtual devices", len(devices) == 8, f"got {len(devices)}")
    batches = make_workload()
    settings = ConsensusSettings()

    print("== baseline (single-device process_chunks) ==")
    base = [process_chunks(list(b), settings) for b in batches]
    base_out = outputs(base)
    check("baseline yields successes",
          sum(t.counts[Failure.SUCCESS] for t in base) >= 12,
          f"{sum(t.counts[Failure.SUCCESS] for t in base)}/{N_ZMWS}")

    print("== scaling: ScheduledPipeline over the 8-device pool ==")
    scope = reg.scope()
    with DevicePool(devices, DevicePoolConfig(policy="sticky")) as pool:
        sched = run_scheduled(batches, settings, pool)
        st = pool.status()
    used = [d["device"] for d in st["devices"] if d["tasks_done"] > 0]
    check("output byte-identical to single-device",
          outputs(sched) == base_out)
    check("tallies match", total(sched) == total(base))
    check("work spread over >= 2 devices", len(used) >= 2, f"used={used}")
    check("sticky routing metrics moved",
          sum(scope.counters("ccs_sched_sticky_routes_total").values()) > 0)

    print("== chaos: one device benched mid-run, zero lost ZMWs ==")
    scope = reg.scope()
    with DevicePool(devices, DevicePoolConfig(policy="sticky",
                                              bench_after=1)) as pool:
        sick = pool._workers[0].name
        with faults.active(f"sched.dispatch:error~{sick}"):
            sched = run_scheduled(batches, settings, pool)
        st = pool.status()
    check("run completed with zero lost ZMWs", total(sched) == total(base),
          f"{total(sched)}/{total(base)}")
    check("surviving outputs byte-identical", outputs(sched) == base_out)
    check("sick device benched",
          scope.counter_value("ccs_sched_device_benched_total",
                              device=sick) == 1)
    check("requeues counted",
          scope.counter_value("ccs_sched_requeues_total") >= 1)
    check("no ZMW fell to Other",
          sum(t.counts[Failure.OTHER] for t in sched) == 0)

    print("== serve: fleet engine stays up through a sick device ==")
    from pbccs_tpu.pipeline import PreparedZmw
    from pbccs_tpu.serve.engine import CcsEngine, ServeConfig

    # stub polish: this leg asserts the ENGINE/pool contract (requeue,
    # bench, stay-up); consensus correctness is the scaling leg's job
    def stub_prep(chunk, _settings):
        return None, PreparedZmw(chunk, np.zeros(12, np.int8), [], 0, 0, 0.0)

    def stub_polish(preps, _settings):
        return [(Failure.SUCCESS, None) for _ in preps]

    scope = reg.scope()
    cfg = ServeConfig(max_batch=BATCH, max_wait_ms=50.0, devices=0)
    eng = CcsEngine(config=cfg, prep_fn=stub_prep, polish_fn=stub_polish)
    eng.start()
    try:
        sick = eng._pool._workers[0].name
        with faults.active(f"sched.dispatch:error~{sick}"):
            reqs = [eng.submit(c) for b in batches for c in b]
            for r in reqs:
                check(f"reply for {r.chunk.id}", r.wait(120.0))
                check(f"{r.chunk.id} completed without error",
                      r.error is None, str(r.error))
        status = eng.status()
        check("engine still answers status",
              status["engine"] == "ccs-serve")
        check("status has per-device breakdown",
              len(status["sched"]["devices"]) == 8)
    finally:
        drained = eng.close()
    check("engine drained cleanly", drained)
    check("serve leg counted requeues",
          scope.counter_value("ccs_sched_requeues_total") >= 1)

    print("sched smoke: all checks passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
