#!/usr/bin/env python
"""Autopilot smoke for the tier-1 gate: a supervised `ccs fleet` under
streamed load, with chaos aimed at the CONTROL plane.

tools/fleet_smoke.py proves the data plane (router + replicas, zero
loss on kill -9 / drain).  This gate proves the autopilot above it --
the supervisor must keep the fleet serving, elastic, and upgradeable
without losing a single request:

  kill9     2-replica fleet, 12 requests in flight; one replica's child
            process is kill -9'd via the pid the supervisor publishes:
            zero lost / zero duplicated (raw-frame counting), answers
            byte-identical to offline process_chunks, the slot respawns
            under a NEW port and rejoins the routing table (respawn +
            add fleet_events in the perf ledger)
  scale     a doubled workload sustains router queue depth past the
            burn threshold: a THIRD slot spawns (scale_up), then the
            idle fleet retires it again by a proven drain (scale_down,
            active slots back to 2)
  rolling   `fleet restart` is issued mid-stream: every slot cycles
            (drain -> SIGTERM -> respawn warm -> health gate), replies
            stay byte-identical to offline, rolling_restart_begin/
            _step/_done land in the ledger
  crashloop a second fleet arms `serve.start:crashloop~1` fault
            injection: slot 1's child dies at every spawn, the
            supervisor quarantines it after K rapid deaths (state
            `dead` with a structured crash-loop reason, rendered by
            `ccs top`), and the surviving slot serves the full
            workload byte-identically

The workload reuses the chaos-cell geometry (tpl 60, 5 passes, seed
20260803), so compiled shapes come warm from the checkout-local
compile cache the earlier smokes populated -- which is also what makes
respawned replicas "warm-started" rather than recompiling.

Run:  JAX_PLATFORMS=cpu python tools/autopilot_smoke.py
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time

sys.path.insert(0, ".")   # repo root (pbccs_tpu)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import fleet_smoke
from fleet_smoke import (artifacts_dir, check, make_workload, router_status,
                         router_verb, run_leg, spawn_ready, wait_for_victim)

N_ZMWS = fleet_smoke.N_ZMWS


def spawn_fleet(extra: list[str], ledger: str,
                faults: str | None = None):
    """One `ccs fleet` control-plane subprocess, ready to administer.
    `faults` rides the environment so the spec reaches the CHILD
    processes the supervisor spawns (the fleet process itself has no
    armed serve.start site)."""
    argv = ["fleet", "--port", "0", "--logLevel", "ERROR",
            "--routerHealthInterval", "0.3", "--routerHealthTimeout", "3",
            "--readyTimeout", "300", "--perfLedger", ledger,
            "--serveArg=--maxBatch=4", "--serveArg=--maxWaitMs=250",
            "--serveArg=--drainTimeout=300"] + extra
    if faults is not None:
        os.environ["PBCCS_FAULTS"] = faults
    try:
        proc, port, _pre = spawn_ready(argv, "CCS-FLEET-READY")
    finally:
        os.environ.pop("PBCCS_FAULTS", None)
    return proc, port


def supervisor_block(port: int) -> dict:
    return router_status(port).get("supervisor", {})


def slots_by_state(port: int) -> dict[int, dict]:
    return {s["slot"]: s for s in supervisor_block(port).get("slots", ())}


def wait_slots(port: int, want, deadline_s: float = 240.0,
               label: str = "") -> dict[int, dict]:
    """Block until `want(slots_dict)` holds; return the slot table."""
    t0 = time.monotonic()
    slots: dict[int, dict] = {}
    while time.monotonic() - t0 < deadline_s:
        slots = slots_by_state(port)
        if want(slots):
            return slots
        time.sleep(0.25)
    raise SystemExit(f"autopilot smoke: timeout waiting for {label}: "
                     f"{json.dumps(list(slots.values()))}")


def ledger_events(path: str) -> list[str]:
    names = []
    try:
        with open(path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("kind") == "fleet_event":
                    names.append(rec["fleet_event"])
    except OSError:
        pass
    return names


def terminate_fleet(proc: subprocess.Popen, label: str) -> None:
    if proc.poll() is None:
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=420)
        check(f"{label}: fleet exited 0 on SIGTERM", rc == 0,
              f"exit {rc}")


def main() -> int:
    from pbccs_tpu.pipeline import ConsensusSettings, process_chunks
    from pbccs_tpu.runtime.cache import enable_compilation_cache
    from pbccs_tpu.runtime.logging import Logger, LogLevel

    enable_compilation_cache()
    Logger.default(Logger(level=LogLevel.ERROR))
    chunks, wires = make_workload()
    out_dir = artifacts_dir()
    ledger_a = os.path.join(out_dir, "autopilot_fleet.ndjson")
    ledger_b = os.path.join(out_dir, "autopilot_crashloop.ndjson")
    for p in (ledger_a, ledger_b):
        if os.path.exists(p):
            os.unlink(p)

    print("== baseline (offline process_chunks) ==", flush=True)
    t0 = time.monotonic()
    offline = process_chunks(list(chunks), ConsensusSettings())
    offline_out = {r.id: (r.sequence, r.qualities)
                   for r in offline.results}
    check("baseline yields all successes", len(offline_out) == N_ZMWS,
          f"{len(offline_out)}/{N_ZMWS} in {time.monotonic() - t0:.0f}s")

    proc, port = spawn_fleet(
        ["--replicas", "2", "--minReplicas", "2", "--maxReplicas", "3",
         "--scaleUpPending", "6", "--scaleUpSustain", "1",
         "--scaleDownIdle", "4", "--backoffBase", "0.2",
         "--drainTimeout", "300", "--crashloopThreshold", "3"],
        ledger_a)
    try:
        wait_slots(port, lambda s: len(s) == 2 and all(
            v["state"] == "up" for v in s.values()), label="2 slots up")

        print("== leg: child kill -9 -> respawn under a new port ==",
              flush=True)
        killed: dict = {}

        def kill9():
            victim = wait_for_victim(port)
            slots = slots_by_state(port)
            slot = next(s for s in slots.values()
                        if s["replica"] == victim)
            os.kill(slot["pid"], signal.SIGKILL)
            killed.update(slot)
            print(f"  kill -9 slot {slot['slot']} "
                  f"(pid {slot['pid']}, {victim})", flush=True)

        results = run_leg("kill9", port, wires, "k", kill9)
        got = {m["zmw"]: (m["sequence"], m["qual"])
               for m in results.values()}
        check("kill9: byte-identical to offline", got == offline_out)
        slots = wait_slots(
            port, lambda s: s.get(killed["slot"], {}).get("state") == "up"
            and s[killed["slot"]]["pid"] != killed["pid"],
            label="killed slot respawned")
        check("kill9: slot respawned under a NEW replica identity",
              slots[killed["slot"]]["replica"] != killed["replica"],
              f"{killed['replica']} -> {slots[killed['slot']]['replica']}")
        evs = ledger_events(ledger_a)
        check("kill9: respawn + add fleet_events in the ledger",
              "respawn" in evs and evs.count("add") >= 3, str(evs))

        print("== leg: load ramp scales up, idle drains back down ==",
              flush=True)
        doubled = list(wires) * 2
        results = run_leg("scale", port, doubled, "s", lambda: None)
        got = {m["zmw"]: (m["sequence"], m["qual"])
               for m in results.values()}
        check("scale: byte-identical to offline", got == offline_out)
        slots = wait_slots(port, lambda s: len(s) >= 3,
                           label="third slot spawned")
        check("scale: scale_up decision in the ledger",
              "scale_up" in ledger_events(ledger_a))
        wait_slots(
            port, lambda s: sum(1 for v in s.values()
                                if v["state"] == "up") == 2
            and any(v["state"] == "stopped" for v in s.values()),
            label="idle slot retired by drain")
        check("scale: scale_down decision in the ledger",
              "scale_down" in ledger_events(ledger_a))

        print("== leg: rolling restart mid-stream ==", flush=True)
        pids_before = {s["slot"]: s["pid"]
                       for s in slots_by_state(port).values()
                       if s["state"] == "up"}

        def rolling():
            rr = router_verb(port, {"verb": "fleet", "id": "rr",
                                    "action": "restart"})
            check("rolling: restart accepted",
                  rr.get("state") == "started", str(rr))
            print("  rolling restart begun mid-stream", flush=True)

        results = run_leg("rolling", port, wires, "r", rolling)
        got = {m["zmw"]: (m["sequence"], m["qual"])
               for m in results.values()}
        check("rolling: byte-identical to offline", got == offline_out)
        wait_slots(
            port, lambda s: "rolling_restart_done"
            in ledger_events(ledger_a)
            and all(v["state"] in ("up", "stopped")
                    for v in s.values()),
            label="rolling restart done")
        evs = ledger_events(ledger_a)
        check("rolling: begin/step/done in the ledger",
              "rolling_restart_begin" in evs
              and evs.count("rolling_restart_step") >= 2
              and "rolling_restart_done" in evs, str(evs))
        pids_after = {s["slot"]: s["pid"]
                      for s in slots_by_state(port).values()
                      if s["state"] == "up"}
        cycled = [sid for sid in pids_before
                  if pids_after.get(sid) not in (None,
                                                 pids_before[sid])]
        check("rolling: every up slot runs a NEW child process",
              len(cycled) == len(pids_before),
              f"cycled {cycled} of {sorted(pids_before)}")

        terminate_fleet(proc, "autopilot")
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(10)

    print("== leg: crash-looping replica is quarantined ==", flush=True)
    proc, port = spawn_fleet(
        ["--replicas", "2", "--backoffBase", "0.1",
         "--crashloopThreshold", "3", "--crashloopWindow", "60",
         "--drainTimeout", "300"],
        ledger_b, faults="serve.start:crashloop~1")
    try:
        slots = wait_slots(
            port, lambda s: s.get(1, {}).get("state") == "dead"
            and s.get(0, {}).get("state") == "up",
            label="slot 1 quarantined, slot 0 up")
        check("crashloop: structured quarantine reason",
              "crash-loop" in slots[1]["reason"], slots[1]["reason"])
        check("crashloop: quarantine fleet_event in the ledger",
              "quarantine" in ledger_events(ledger_b))

        # the operator view tells a dead slot from a live one
        top = subprocess.run(
            [sys.executable, "-m", "pbccs_tpu.cli", "top",
             f"127.0.0.1:{port}", "--once", "--format", "json"],
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
            capture_output=True, text=True, timeout=120)
        check("crashloop: ccs top --once exits 0", top.returncode == 0,
              top.stderr[-300:])
        view = json.loads(top.stdout)
        states = {r.get("slot"): r.get("slot_state")
                  for r in view["replicas"] if "slot" in r}
        check("crashloop: ccs top renders the dead slot",
              states.get(1) == "dead", str(states))
        check("crashloop: ccs top renders the live slot",
              states.get(0) == "up", str(states))

        # the crippled fleet still answers EVERYTHING, correctly
        results = run_leg("crashloop", port, wires, "c", lambda: None)
        got = {m["zmw"]: (m["sequence"], m["qual"])
               for m in results.values()}
        check("crashloop: byte-identical to offline", got == offline_out)

        terminate_fleet(proc, "crashloop")
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(10)

    print(f"  artifacts: {ledger_a} "
          f"({len(ledger_events(ledger_a))} fleet events), {ledger_b} "
          f"({len(ledger_events(ledger_b))} fleet events)", flush=True)
    print("autopilot smoke: all checks passed", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
