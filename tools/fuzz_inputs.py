#!/usr/bin/env python
"""Structured input fuzzer: hostile bytes at every front door.

Generates a VALID workload (a multi-block subread BAM and an NDJSON
serve session), applies seeded structured corruptions, and asserts the
input-hardening invariant:

    the process survives, valid records decode byte-identical to the
    uncorrupted run, and every rejection moves a {reason}-labeled
    counter -- corruption degrades a record or a session, never the run.

Corruption classes (each deterministic from (seed, class) alone, so any
finding reproduces with `--seed S --only CLASS`):

  compressed layer   bam:bitflip (one flipped bit mid-stream),
                     bam:truncate (cut at a random byte),
                     bam:torn_final (final block cut short)
  record layer       bam:blocklen_huge / bam:blocklen_lie (length-field
                     lies), bam:tagtype (unknown tag type),
                     bam:nibble (non-ACGT base), bam:bad_snr (inf SNR),
                     bam:header_magic (clobbered BAM magic)
  wire protocol      wire:oversized_frame, wire:binary_garbage,
                     wire:bad_json, wire:bad_zmw, wire:idle_session,
                     wire:inflight_cap -- run against the plaintext
                     front doors (serve + router) AND their TLS
                     listeners (wire-tls:* / router-wire-tls:*, which
                     also prove a plaintext client is dropped with a
                     counted tls_handshake abort, never a traceback)
  process            drain: kill -TERM a live `ccs serve` -> it reports
                     CCS-SERVE-DRAINING, drains in flight, exits 0

`--smoke --seed 0` (the tier-1 leg) runs every class once plus a
consensus-parity check (surviving ZMWs of a corrupted BAM polish
byte-identical to the clean run).  `--rounds N` (chaos_bench's longer
leg) re-rolls randomized corruption positions N times over the decode
classes.

Usage:
    JAX_PLATFORMS=cpu python tools/fuzz_inputs.py --smoke --seed 0
    JAX_PLATFORMS=cpu python tools/fuzz_inputs.py --rounds 50 --seed 7
    JAX_PLATFORMS=cpu python tools/fuzz_inputs.py --seed 0 --only bam:bitflip
"""

from __future__ import annotations

import argparse
import io
import json
import os
import signal
import socket
import struct
import subprocess
import sys
import tempfile
import time
import zlib

import numpy as np

sys.path.insert(0, ".")  # runnable as tools/fuzz_inputs.py from the repo root

from pbccs_tpu.io import bam as bamio
from pbccs_tpu.obs.metrics import default_registry

_REG = default_registry()

# mirror chaos_smoke's workload (6 ZMWs, tpl 60, 5 passes) for the
# consensus leg so its compiled shapes are already cached in tier-1
CONSENSUS_SEED = 20260803


class CheckFailed(AssertionError):
    pass


def check(report: dict, name: str, ok: bool, detail: str = "") -> None:
    report[name] = bool(ok) if not detail else f"{bool(ok)} ({detail})"
    print(f"  {'PASS' if ok else 'FAIL'}  {name}"
          + (f"  ({detail})" if detail else ""))
    if not ok:
        raise CheckFailed(name)


# ------------------------------------------------------------- BAM workload

class BamWorkload:
    """A valid multi-block BAM kept in mutable parts: header blob +
    per-record encoded blobs, so corruption classes can lie about
    specific fields before compression."""

    def __init__(self, seed: int, n_records: int = 48, seq_len: int = 3500):
        rng = np.random.default_rng([seed, 0xBA])
        text = bamio.BamHeader(
            read_groups=[bamio.ReadGroupInfo("fuzz")]).to_text().encode()
        self.header_blob = (b"BAM\x01" + struct.pack("<i", len(text)) + text
                            + struct.pack("<i", 0))
        self.records: list[bamio.BamRecord] = []
        self.rec_blobs: list[bytes] = []
        for i in range(n_records):
            seq = "".join("ACGT"[b] for b in rng.integers(0, 4, seq_len))
            qual = "".join(chr(33 + int(q))
                           for q in rng.integers(10, 50, seq_len))
            rec = bamio.BamRecord(
                name=f"fuzz/{i}/0_{seq_len}", seq=seq, qual=qual,
                tags={"RG": bamio.make_read_group_id("fuzz", "SUBREAD"),
                      "zm": i, "cx": 3, "rq": 0.85,
                      "sn": [7.0, 8.0, 9.0, 10.0]})
            self.records.append(rec)
            self.rec_blobs.append(bamio.encode_record(rec))

    def payload(self, rec_blobs: list[bytes] | None = None) -> bytes:
        return self.header_blob + b"".join(rec_blobs or self.rec_blobs)

    def compress(self, payload: bytes | None = None) -> bytes:
        buf = io.BytesIO()
        w = bamio.BgzfWriter(buf)
        w.write(payload if payload is not None else self.payload())
        w.close()
        return buf.getvalue()

    def baseline(self, tmp: str) -> dict[str, tuple]:
        """Fingerprints of a STRICT decode of the clean bytes (not the
        in-memory records: float tags round-trip through f32)."""
        if not hasattr(self, "_baseline"):
            records, _, _, _ = _decode(self.compress(), "strict", tmp)
            self._baseline = {r.name: _fingerprint(r) for r in records}
        return self._baseline


def _fingerprint(rec: bamio.BamRecord) -> tuple:
    return (rec.seq, rec.qual, rec.flag,
            json.dumps(rec.tags, sort_keys=True, default=str))


def _mutate_blob(blob: bytes, sig: bytes, offset: int,
                 replacement: bytes) -> bytes:
    """Replace bytes at (index of sig) + offset inside one record blob."""
    at = blob.index(sig) + offset
    return blob[:at] + replacement + blob[at + len(replacement):]


# Each corruption: fn(workload, rng) -> (corrupt_bytes, detail, hints).
# hints: lost_names (exactly these records vanish), expect_reasons (at
# least one of these counters moves), max_lost_salvage (salvage recovery
# bound; None = suffix loss, no bound), prefix_only (survivors must be a
# baseline prefix).

def _c_bitflip(w: BamWorkload, rng) -> tuple:
    data = bytearray(w.compress())
    # flip inside a middle block's payload: past the first block, clear
    # of the EOF marker
    pos = int(rng.integers(70_000, len(data) - 200))
    data[pos] ^= 1 << int(rng.integers(0, 8))
    per_block = (64 * 1024 - 512) // len(w.rec_blobs[0]) + 2
    return bytes(data), f"bit flipped at byte {pos}", dict(
        expect_reasons={"bgzf_block"}, max_lost_salvage=per_block + 2)


def _c_truncate(w: BamWorkload, rng) -> tuple:
    data = w.compress()
    pos = int(rng.integers(len(data) // 3, len(data) - 100))
    return data[:pos], f"truncated at byte {pos}/{len(data)}", dict(
        expect_reasons={"truncated_block", "truncated_record",
                        "missing_eof_marker", "bgzf_block"},
        prefix_only=True)


def _c_torn_final(w: BamWorkload, rng) -> tuple:
    data = w.compress()
    cut = int(rng.integers(5, 40))
    return data[:-cut], f"final {cut} bytes torn off", dict(
        expect_reasons={"truncated_block", "truncated_record",
                        "missing_eof_marker", "bgzf_block"},
        prefix_only=True)


def _c_blocklen_huge(w: BamWorkload, rng) -> tuple:
    k = int(rng.integers(1, len(w.rec_blobs) - 1))
    blobs = list(w.rec_blobs)
    blobs[k] = struct.pack("<i", 1 << 30) + blobs[k][4:]
    return w.compress(w.payload(blobs)), \
        f"record {k} block_size -> 1<<30", dict(
            expect_reasons={"block_size"}, max_lost_salvage=1,
            prefix_lenient=True)


def _c_blocklen_lie(w: BamWorkload, rng) -> tuple:
    k = int(rng.integers(1, len(w.rec_blobs) - 1))
    blobs = list(w.rec_blobs)
    true_len = struct.unpack_from("<i", blobs[k])[0]
    blobs[k] = struct.pack("<i", true_len - 40) + blobs[k][4:]
    return w.compress(w.payload(blobs)), \
        f"record {k} block_size {true_len} -> {true_len - 40}", dict(
            expect_reasons={"seq_qual", "overflow", "block_size",
                            "tag_overflow", "name", "tag_type"},
            max_lost_salvage=3, prefix_lenient=True)


def _c_tagtype(w: BamWorkload, rng) -> tuple:
    k = int(rng.integers(0, len(w.rec_blobs)))
    blobs = list(w.rec_blobs)
    blobs[k] = _mutate_blob(blobs[k], b"zmi", 2, b"q")
    return w.compress(w.payload(blobs)), \
        f"record {k} zm tag type i -> q", dict(
            lost_names={w.records[k].name},
            expect_reasons={"tag_type"}, max_lost_salvage=1)


def _c_nibble(w: BamWorkload, rng) -> tuple:
    k = int(rng.integers(0, len(w.rec_blobs)))
    blobs = list(w.rec_blobs)
    blob = blobs[k]
    seq_off = 4 + 32 + len(w.records[k].name) + 1
    blobs[k] = blob[:seq_off] + b"\xff" + blob[seq_off + 1:]  # two N's
    return w.compress(w.payload(blobs)), \
        f"record {k} first seq byte -> 0xFF (NN)", dict(
            lost_names={w.records[k].name},
            expect_reasons={"non_acgt"}, max_lost_salvage=1)


def _c_bad_snr(w: BamWorkload, rng) -> tuple:
    k = int(rng.integers(0, len(w.rec_blobs)))
    blobs = list(w.rec_blobs)
    inf = struct.pack("<f", float("inf"))
    blobs[k] = _mutate_blob(blobs[k], b"snBf", 8, inf)
    return w.compress(w.payload(blobs)), \
        f"record {k} sn[0] -> inf", dict(
            lost_names={w.records[k].name},
            expect_reasons={"bad_snr"}, max_lost_salvage=1)


def _c_header_magic(w: BamWorkload, rng) -> tuple:
    payload = b"XAM\x02" + w.payload()[4:]
    return w.compress(payload), "BAM magic clobbered", dict(
        expect_reasons={"header"}, max_lost_salvage=2,
        prefix_lenient=True)


BAM_CLASSES = [
    ("bam:bitflip", _c_bitflip),
    ("bam:truncate", _c_truncate),
    ("bam:torn_final", _c_torn_final),
    ("bam:blocklen_huge", _c_blocklen_huge),
    ("bam:blocklen_lie", _c_blocklen_lie),
    ("bam:tagtype", _c_tagtype),
    ("bam:nibble", _c_nibble),
    ("bam:bad_snr", _c_bad_snr),
    ("bam:header_magic", _c_header_magic),
]


def _decode(data: bytes, policy: str, tmp: str):
    path = os.path.join(tmp, f"case_{policy}.bam")
    with open(path, "wb") as f:
        f.write(data)
    scope = _REG.scope()
    reader = bamio.BamReader(path, policy=policy)
    records = list(reader)
    reader.close()
    rejected = sum(scope.counters(
        "ccs_input_invalid_records_total").values())
    salvaged = scope.counter_value("ccs_input_salvaged_blocks_total")
    return records, reader.stats, rejected, salvaged


def run_bam_case(name: str, corrupt_fn, workload: BamWorkload, seed: int,
                 tmp: str, report: dict) -> None:
    # rng derived from (seed, class name) ALONE: any finding reproduces
    # with `--seed S --only CLASS` (crc32, not hash(): PYTHONHASHSEED
    # must not change where corruption lands)
    rng = np.random.default_rng([seed, zlib.crc32(name.encode())])
    data, detail, hints = corrupt_fn(workload, rng)
    baseline = workload.baseline(tmp)
    base_names = [r.name for r in workload.records]
    print(f"CASE {name} seed={seed} ({detail})")
    for policy in ("lenient", "salvage"):
        tag = f"{name}:{policy}"
        try:
            records, stats, rejected, salvaged = _decode(data, policy, tmp)
        except Exception as e:  # noqa: BLE001 -- the invariant under test
            check(report, f"{tag}:survives", False, repr(e))
            return
        check(report, f"{tag}:survives", True)
        # every yielded record is byte-identical to its baseline twin;
        # no fabricated records
        clean = all(r.name in baseline
                    and _fingerprint(r) == baseline[r.name]
                    for r in records)
        check(report, f"{tag}:valid_records_identical", clean,
              f"{len(records)}/{len(base_names)} decoded")
        lost = set(base_names) - {r.name for r in records}
        if lost:
            counted = rejected + salvaged + (1 if stats.bytes_lost else 0)
            check(report, f"{tag}:rejections_counted", counted > 0,
                  f"{len(lost)} lost, {rejected} rejections, "
                  f"{int(salvaged)} resyncs, {stats.bytes_lost}B lost")
        if hints.get("expect_reasons") and (lost or rejected):
            moved = set(stats.invalid_records) & hints["expect_reasons"]
            check(report, f"{tag}:reason_labeled", bool(moved),
                  f"moved={sorted(stats.invalid_records)} "
                  f"expected one of {sorted(hints['expect_reasons'])}")
        # a framing loss truncates lenient decode to a valid prefix; a
        # content-level skip costs exactly the hit record in both modes
        if hints.get("prefix_only") or (policy == "lenient"
                                        and hints.get("prefix_lenient")):
            got = [r.name for r in records]
            check(report, f"{tag}:prefix_preserved",
                  got == base_names[:len(got)])
        if hints.get("lost_names") is not None:
            check(report, f"{tag}:exact_loss",
                  lost == hints["lost_names"], f"lost={sorted(lost)}")
        if policy == "salvage" and hints.get("max_lost_salvage") is not None:
            check(report, f"{tag}:salvage_recovery",
                  len(lost) <= hints["max_lost_salvage"],
                  f"{len(lost)} lost <= {hints['max_lost_salvage']}")


# --------------------------------------------------------- consensus parity

def leg_consensus_parity(tmp: str, report: dict) -> None:
    """Acceptance invariant: valid records' CONSENSUS output is
    byte-identical to the uncorrupted run (decode identity implies it,
    but this leg proves it end to end through the polish pipeline)."""
    print("== leg: consensus parity under corruption ==")
    from pbccs_tpu.models.arrow.params import decode_bases, encode_bases
    from pbccs_tpu.pipeline import Chunk, Subread, process_chunks
    from pbccs_tpu.simulate import simulate_zmw

    rng = np.random.default_rng(CONSENSUS_SEED)
    w = BamWorkload.__new__(BamWorkload)
    text = bamio.BamHeader(
        read_groups=[bamio.ReadGroupInfo("fuzzc")]).to_text().encode()
    w.header_blob = (b"BAM\x01" + struct.pack("<i", len(text)) + text
                     + struct.pack("<i", 0))
    w.records, w.rec_blobs = [], []
    for i in range(6):
        _, reads, _, snr = simulate_zmw(rng, 60, 5)
        for k, r in enumerate(reads):
            rec = bamio.BamRecord(
                name=f"fuzzc/{i}/{k}_{k + 1}", seq=decode_bases(r), qual="",
                tags={"zm": i, "cx": 3, "rq": 0.85,
                      "sn": [float(s) for s in snr]})
            w.records.append(rec)
            w.rec_blobs.append(bamio.encode_record(rec))

    def chunks_from(records):
        by_zmw: dict[str, Chunk] = {}
        for r in records:
            zid = "/".join(r.name.split("/")[:2])
            c = by_zmw.setdefault(
                zid, Chunk(zid, [], np.asarray(r.tags["sn"], np.float64)))
            c.reads.append(Subread(r.name, encode_bases(r.seq), flags=3,
                                   read_accuracy=float(r.tags["rq"])))
        return [by_zmw[k] for k in sorted(by_zmw)]

    # corrupt one subread of ZMW 2 (tag type) -> lenient drops that read
    hit = next(i for i, r in enumerate(w.records)
               if r.name.startswith("fuzzc/2/"))
    blobs = list(w.rec_blobs)
    blobs[hit] = _mutate_blob(blobs[hit], b"zmi", 2, b"q")
    clean_path = os.path.join(tmp, "consensus_clean.bam")
    with open(clean_path, "wb") as f:
        f.write(w.compress())
    dirty = w.compress(w.payload(blobs))

    clean_records = list(bamio.BamReader(clean_path, policy="strict"))
    dirty_records, _, _, _ = _decode(dirty, "lenient", tmp)
    base = process_chunks(chunks_from(clean_records))
    fuzz = process_chunks(chunks_from(dirty_records))
    base_out = {r.id: (r.sequence, r.qualities) for r in base.results}
    fuzz_out = {r.id: (r.sequence, r.qualities) for r in fuzz.results}
    untouched = {z for z in base_out if z != "fuzzc/2"}
    check(report, "consensus:survivor_parity",
          all(base_out[z] == fuzz_out.get(z) for z in untouched),
          f"{len(untouched)} untouched ZMWs byte-identical")


# ------------------------------------------------------------ wire protocol

_TLS_CACHE: dict = {}


def _tls_material(tmp: str):
    """One self-signed EC cert per run -> (server_ctx, client_ctx)."""
    if "ctx" not in _TLS_CACHE:
        from pbccs_tpu.serve import tenancy

        cert = os.path.join(tmp, "fuzz-cert.pem")
        key = os.path.join(tmp, "fuzz-key.pem")
        subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "ec", "-pkeyopt",
             "ec_paramgen_curve:prime256v1", "-nodes", "-keyout", key,
             "-out", cert, "-days", "2", "-subj", "/CN=localhost"],
            check=True, capture_output=True)
        _TLS_CACHE["ctx"] = (tenancy.server_ssl_context(cert, key),
                             tenancy.client_ssl_context(cert))
    return _TLS_CACHE["ctx"]


def _stub_server(max_line=4096, idle_s=0.0, cap=64, gate=None,
                 ssl_ctx=None):
    from pbccs_tpu.pipeline import Failure, PreparedZmw
    from pbccs_tpu.serve.engine import CcsEngine, ServeConfig
    from pbccs_tpu.serve.server import CcsServer

    def prep(chunk, settings):
        return None, PreparedZmw(chunk, np.zeros(64, np.int8), [],
                                 len(chunk.reads), 0, 0.0)

    def polish(preps, settings):
        if gate is not None:
            gate.wait(10.0)
        return [(Failure.SUCCESS, None) for _ in preps]

    eng = CcsEngine(config=ServeConfig(
        max_batch=1, max_wait_ms=20.0, max_line_bytes=max_line,
        idle_timeout_s=idle_s, max_inflight_per_session=cap),
        prep_fn=prep, polish_fn=polish).start()
    srv = CcsServer(eng, port=0, ssl_context=ssl_ctx).start()
    return eng, srv


def _stub_front(kind, max_line=4096, idle_s=0.0, cap=64, gate=None,
                ssl_ctx=None):
    """The wire-armor target: either a bare stub `ccs serve` stack, or
    the SAME stack fronted by a one-replica `ccs router` whose session
    armor carries the tight limits (the backend keeps generous ones, so
    every rejection under test is the ROUTER's).  `ssl_ctx` makes the
    FRONT door a TLS listener (the router's backend hop stays local
    plaintext -- the armor under test is the edge).  Returns
    (server-like with .host/.port, teardown callable)."""
    if kind == "serve":
        eng, srv = _stub_server(max_line=max_line, idle_s=idle_s, cap=cap,
                                gate=gate, ssl_ctx=ssl_ctx)

        def teardown():
            srv.shutdown()
            eng.close()

        return srv, teardown
    from pbccs_tpu.serve.router import CcsRouter, RouterConfig, RouterServer

    eng, srv = _stub_server(gate=gate)  # backend: default (loose) armor
    router = CcsRouter(
        [f"127.0.0.1:{srv.port}"],
        RouterConfig(health_interval_s=0.2, max_line_bytes=max_line,
                     idle_timeout_s=idle_s,
                     max_inflight_per_session=cap)).start()
    rsrv = RouterServer(router, port=0, ssl_context=ssl_ctx).start()

    def teardown():
        rsrv.shutdown()
        router.close(drain=False)
        srv.shutdown()
        eng.close()

    return rsrv, teardown


def _session(srv, timeout=10.0, client_ctx=None):
    conn = socket.create_connection((srv.host, srv.port), timeout=timeout)
    if client_ctx is not None:
        conn = client_ctx.wrap_socket(conn, server_hostname=srv.host)
    return conn, conn.makefile("rb")


def _reply(rf):
    line = rf.readline()
    return json.loads(line) if line else None


def leg_wire(report: dict, kind: str = "serve",
             tls_tmp: str | None = None) -> None:
    """The wire-armor invariants, against either front door: the bare
    serve session (`kind="serve"`, tags `wire:*`) or the router session
    in front of a loose-armored replica (`kind="router"`, tags
    `router-wire:*`) -- the oversized-frame / garbage / idle-reap /
    in-flight-cap behavior must be identical at both tiers.  With
    `tls_tmp` the front door is a TLS listener (tags gain `-tls`): the
    same armor must hold through the handshake, and a PLAINTEXT client
    must be dropped with a counted tls_handshake abort."""
    tls = tls_tmp is not None
    w = ("wire" if kind == "serve" else "router-wire") + \
        ("-tls" if tls else "")
    print(f"== leg: wire-protocol armor ({kind} front door"
          f"{', TLS' if tls else ''}) ==")
    from pbccs_tpu.serve import protocol

    server_ctx = client_ctx = None
    if tls:
        server_ctx, client_ctx = _tls_material(tls_tmp)
    scope = _REG.scope()
    srv, teardown = _stub_front(kind, max_line=4096, idle_s=0.5, cap=2,
                                ssl_ctx=server_ctx)
    try:
        if tls:
            # a plaintext client never gets a frame in: the handshake
            # fails and the socket dies (FIN or RST), no traceback
            raw = socket.create_connection((srv.host, srv.port),
                                           timeout=10.0)
            raw.settimeout(10.0)
            raw.sendall(b'{"verb":"ping","id":"p"}\n')
            try:
                data = raw.recv(4096)
            except OSError:
                data = b""
            raw.close()
            check(report, f"{w}:plaintext_rejected", data == b"",
                  f"got {data[:40]!r}")

        # oversized frame -> bad_request, session closed, abort counted
        conn, rf = _session(srv, client_ctx=client_ctx)
        conn.sendall(b"a" * 8192)
        msg = _reply(rf)
        check(report, f"{w}:oversized_frame:bad_request",
              msg is not None and msg.get("code") == "bad_request",
              str(msg)[:80])
        check(report, f"{w}:oversized_frame:session_closed",
              rf.readline() == b"")
        conn.close()

        # binary garbage -> bad_request, session SURVIVES
        conn, rf = _session(srv, client_ctx=client_ctx)
        conn.sendall(b"\xff\xfe\x00garbage\n")
        msg = _reply(rf)
        check(report, f"{w}:binary_garbage:bad_request",
              msg.get("code") == "bad_request")
        conn.sendall(protocol.encode_msg({"verb": "ping", "id": "p"}))
        check(report, f"{w}:binary_garbage:session_survives",
              _reply(rf).get("type") == "pong")
        conn.close()

        # structurally bad JSON + invalid zmw payloads -> structured
        # rejections, each with a machine-readable reason
        conn, rf = _session(srv, client_ctx=client_ctx)
        for payload in (
                b"{not json\n",
                b'{"verb":"submit","id":"x","zmw":"nope"}\n',
                b'{"verb":"submit","id":"x","zmw":{"id":"m/1",'
                b'"snr":[1,2,3],"reads":[{"seq":"ACGT"}]}}\n',
                b'{"verb":"submit","id":"x","zmw":{"id":"m/1",'
                b'"reads":[{"seq":"ACGT","accuracy":7}]}}\n',
                b'{"verb":"submit","id":"x","zmw":{"id":"m/1",'
                b'"reads":[{"seq":""}]}}\n'):
            conn.sendall(payload)
            msg = _reply(rf)
            if msg.get("code") != "bad_request":
                check(report, f"{w}:bad_zmw:rejected", False,
                      f"{payload[:40]!r} -> {msg}")
        check(report, f"{w}:bad_zmw:rejected", True, "5 payloads")
        conn.sendall(protocol.encode_msg({"verb": "ping", "id": "p"}))
        check(report, f"{w}:bad_zmw:session_survives",
              _reply(rf).get("type") == "pong")
        conn.close()

        # idle session -> reaped with a `closed` notice
        conn, rf = _session(srv, client_ctx=client_ctx)
        t0 = time.monotonic()
        msg = _reply(rf)  # blocks until the reaper speaks
        check(report, f"{w}:idle_session:reaped",
              msg is not None and msg.get("type") == "closed"
              and msg.get("reason") == "idle_timeout",
              f"after {time.monotonic() - t0:.2f}s")
        check(report, f"{w}:idle_session:closed", rf.readline() == b"")
        conn.close()
    finally:
        teardown()

    # in-flight cap: gate the polish so submits stack up
    import threading
    gate = threading.Event()
    srv, teardown = _stub_front(kind, cap=2, gate=gate,
                                ssl_ctx=server_ctx)
    try:
        conn, rf = _session(srv, client_ctx=client_ctx)
        for i in range(3):
            conn.sendall(json.dumps(
                {"verb": "submit", "id": f"r{i}",
                 "zmw": {"id": f"m/{i}",
                         "reads": [{"seq": "ACGTACGT"}] * 4}}).encode()
                + b"\n")
        msgs = [_reply(rf) for _ in range(1)]
        check(report, f"{w}:inflight_cap:rejected",
              msgs[0].get("code") == "overloaded"
              and "in-flight cap" in msgs[0].get("error", ""),
              str(msgs[0])[:90])
        gate.set()
        done = [_reply(rf) for _ in range(2)]
        check(report, f"{w}:inflight_cap:others_complete",
              all(m and m.get("type") == "result" for m in done))
        conn.close()
    finally:
        gate.set()
        teardown()
    aborts = scope.counters("ccs_serve_session_aborts_total")
    causes = {dict(k).get("cause") for k in aborts if aborts[k] > 0}
    expected = {"oversized_frame", "idle_timeout"}
    if tls:
        expected = expected | {"tls_handshake"}
    check(report, f"{w}:aborts_counted", expected <= causes,
          f"causes={sorted(causes)}")
    check(report, f"{w}:cap_counted", scope.counter_value(
        "ccs_serve_inflight_cap_rejects_total") >= 1)


# ------------------------------------------------------------ drain (TERM)

def leg_drain(report: dict) -> None:
    """kill -TERM a real `ccs serve` with requests PARKED in the dynamic
    batcher (a 30 s flush wait guarantees they are in flight when the
    signal lands): it must announce the drain, flush + answer every one,
    and exit 0.  The workload mirrors chaos_smoke's 6-ZMW cell so the
    drain-triggered polish hits the same compiled-program cache."""
    print("== leg: SIGTERM graceful drain ==")
    from pbccs_tpu.models.arrow.params import decode_bases
    from pbccs_tpu.serve import protocol
    from pbccs_tpu.simulate import simulate_zmw

    rng = np.random.default_rng(CONSENSUS_SEED)
    zmws = []
    for i in range(6):
        _, reads, _, snr = simulate_zmw(rng, 60, 5)
        zmws.append({"id": f"smoke/{i}", "snr": [float(s) for s in snr],
                     "reads": [{"seq": decode_bases(r)} for r in reads]})

    proc = subprocess.Popen(
        [sys.executable, "-m", "pbccs_tpu.cli", "serve", "--port", "0",
         "--maxBatch", "16", "--maxWaitMs", "30000",
         "--drainTimeout", "300", "--logLevel", "ERROR"],
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
    try:
        line = proc.stdout.readline()
        check(report, "drain:ready", line.startswith("CCS-SERVE-READY"),
              line.strip())
        _, host, port = line.split()
        conn = socket.create_connection((host, int(port)), timeout=300.0)
        rf = conn.makefile("rb")
        for i, z in enumerate(zmws):
            conn.sendall(protocol.encode_msg(
                {"verb": "submit", "id": f"d{i}", "zmw": z}))
        # confirm every submit is admitted-and-parked (the 30 s flush
        # wait means none can complete) before the signal lands
        conn.sendall(protocol.encode_msg({"verb": "status", "id": "st"}))
        status = _reply(rf)
        while status is not None and status.get("id") != "st":
            status = _reply(rf)
        check(report, "drain:in_flight_before_term",
              status is not None and status.get("pending") == len(zmws),
              f"pending={status and status.get('pending')}")
        proc.send_signal(signal.SIGTERM)
        results = {}
        while len(results) < len(zmws):
            msg = _reply(rf)
            if msg is None:
                break
            if msg.get("type") == "result":
                results[msg.get("id")] = msg.get("status")
            elif msg.get("type") == "error":
                results[msg.get("id")] = msg.get("code")
        check(report, "drain:in_flight_answered",
              len(results) == len(zmws), f"statuses={sorted(results.items())}")
        drain_line = proc.stdout.readline()
        check(report, "drain:announced",
              drain_line.startswith("CCS-SERVE-DRAINING"),
              drain_line.strip())
        rc = proc.wait(timeout=300)
        check(report, "drain:exit_zero", rc == 0, f"exit {rc}")
        check(report, "drain:results_not_aborted",
              all(s not in ("closed", "internal") for s in results.values()),
              f"{sorted(set(results.values()))}")
        conn.close()
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(10)


# ------------------------------------------------------------------- driver

def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--smoke", action="store_true",
                   help="deterministic tier-1 leg: every class once + "
                        "consensus parity + wire armor + TERM drain")
    p.add_argument("--rounds", type=int, default=0,
                   help="extra randomized decode rounds (chaos_bench)")
    p.add_argument("--only", default=None,
                   help="run one corruption class (e.g. bam:bitflip)")
    p.add_argument("--skip-subprocess", action="store_true",
                   help="skip the TERM-drain subprocess leg")
    p.add_argument("--out", default=None, help="also write the JSON here")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from pbccs_tpu.runtime.logging import Logger, LogLevel

    Logger.default(Logger(level=LogLevel.FATAL))
    report: dict = {"seed": args.seed}
    failed = False
    tmp = tempfile.mkdtemp(prefix="fuzz_inputs_")
    try:
        classes = [(n, f) for n, f in BAM_CLASSES
                   if args.only in (None, n)]
        if classes:
            workload = BamWorkload(args.seed)
            # self-check: the uncorrupted workload decodes losslessly
            clean, stats, _, _ = _decode(workload.compress(), "strict", tmp)
            check(report, "workload:clean_roundtrip",
                  [r.name for r in clean]
                  == [r.name for r in workload.records]
                  and stats.total_invalid == 0,
                  f"{len(clean)} records, multi-block="
                  f"{len(workload.payload()) > 2 * 64 * 1024}")
            for name, fn in classes:
                run_bam_case(name, fn, workload, args.seed, tmp, report)
            for r in range(args.rounds):
                seed_r = args.seed * 1000 + r + 1
                name, fn = classes[r % len(classes)]
                run_bam_case(name, fn, workload, seed_r, tmp, report)
        if args.smoke and args.only is None:
            leg_wire(report)
            leg_wire(report, kind="router")
            leg_wire(report, tls_tmp=tmp)
            leg_wire(report, kind="router", tls_tmp=tmp)
            leg_consensus_parity(tmp, report)
            if not args.skip_subprocess:
                leg_drain(report)
        elif args.only and args.only.startswith("wire:"):
            leg_wire(report)
        elif args.only and args.only.startswith("router-wire:"):
            leg_wire(report, kind="router")
        elif args.only and args.only.startswith("wire-tls:"):
            leg_wire(report, tls_tmp=tmp)
        elif args.only and args.only.startswith("router-wire-tls:"):
            leg_wire(report, kind="router", tls_tmp=tmp)
        elif args.only == "drain":
            leg_drain(report)
    except CheckFailed as e:
        report["failed"] = str(e)
        failed = True

    out = json.dumps(report, indent=2, default=str)
    if args.out:
        with open(args.out, "w") as f:
            f.write(out + "\n")
    print("fuzz_inputs:", "FAILED" if failed else "all checks passed")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
