#!/usr/bin/env python
"""Cross-validate the polish stage on the reference's REAL subread fixture.

Runs the pipeline's own draft stage (filter -> POA -> extract) on the
m140905 real ZMW (10 subread passes, ~600 bp insert -- the fixture the
reference uses in tests/TestSparsePoa.cpp:150-170), then polishes the SAME
prepared inputs two ways:

  1. this framework's BatchPolisher (the TPU path; CPU backend works too);
  2. the reference's own compiled C++ Arrow implementation
     (native/refbench, READWIN per-read windows),

and compares the polished consensus bit-for-bit plus the BAM-clamped QV
strings.  This is the same-draw protocol the simulated cross-validation
already uses (127/128 bit-identical at round 2), now on real data.

Usage:  python tools/crossval_real.py         # prints a JSON verdict line
Exit 0 iff the consensus sequences are identical.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

FIXTURE = ("/root/reference/tests/data/m140905_042212_sidney_"
           "c100564852550000001823085912221377_s1_X0.fasta")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REFBENCH = os.path.join(REPO, "native", "refbench", "build", "refbench")


def prepare():
    import numpy as np

    from pbccs_tpu.io.fasta import read_fasta
    from pbccs_tpu.pipeline import (Chunk, ConsensusSettings, Subread,
                                    prepare_chunk)

    chunk = Chunk("m140905/6251", [], np.full(4, 8.0))
    for name, seq in read_fasta(FIXTURE):
        chunk.reads.append(Subread.from_str(name, seq))
    settings = ConsensusSettings(min_passes=3)
    failure, prep = prepare_chunk(chunk, settings)
    assert failure is None, f"draft stage failed: {failure}"
    return prep, settings


def polish_ours(prep, settings):
    import numpy as np

    from pbccs_tpu.models.arrow.params import decode_bases
    from pbccs_tpu.parallel.batch import BatchPolisher, ZmwTask

    task = ZmwTask(prep.chunk.id, prep.css, np.asarray(prep.chunk.snr),
                   [m.seq for m in prep.mapped],
                   [m.strand for m in prep.mapped],
                   [m.tpl_start for m in prep.mapped],
                   [m.tpl_end for m in prep.mapped])
    polisher = BatchPolisher([task], min_zscore=settings.min_zscore)
    res = polisher.refine(settings.refine)
    qvs = polisher.consensus_qvs()[0]
    qstr = "".join(chr(min(max(0, int(q)), 93) + 33) for q in qvs)
    # read windows in the FINAL consensus frame (refinement remaps them
    # through every applied indel)
    n = len(prep.mapped)
    windows = list(zip(polisher._tstarts[0, :n].tolist(),
                       polisher._tends[0, :n].tolist()))
    return decode_bases(polisher.tpls[0]), qstr, res[0], windows


def polish_reference(prep, settings):
    from pbccs_tpu.models.arrow.params import decode_bases

    assert os.path.exists(REFBENCH), \
        f"{REFBENCH} missing: make -C native/refbench"
    with tempfile.TemporaryDirectory(prefix="crossval_") as tmp:
        wl = os.path.join(tmp, "workload.txt")
        dump = os.path.join(tmp, "dump.txt")
        snr = prep.chunk.snr
        with open(wl, "w") as f:
            # both sides MUST run the same refinement budget for the
            # bit-identity comparison to be meaningful
            f.write(f"CONFIG 1 {len(prep.css)} {len(prep.mapped)} "
                    f"{settings.refine.max_iterations} "
                    f"{settings.min_zscore}\n")
            f.write(f"ZMW {prep.chunk.id.replace('/', '_')} "
                    f"{snr[0]} {snr[1]} {snr[2]} {snr[3]} "
                    f"{len(prep.mapped)}\n")
            f.write(f"DRAFT {decode_bases(prep.css)}\n")
            for m in prep.mapped:
                f.write(f"READWIN {m.strand} {m.tpl_start} {m.tpl_end} "
                        f"{decode_bases(m.seq)}\n")
        out = subprocess.run([REFBENCH, wl, "--dump", dump],
                             capture_output=True, text=True, check=True)
        stats = json.loads(out.stdout.strip().splitlines()[-1])
        with open(dump) as f:
            _, tpl, qstr = f.read().split()
    return tpl, qstr, stats


def main() -> int:
    prep, settings = prepare()
    ours, our_q, res, _ = polish_ours(prep, settings)
    ref, ref_q, stats = polish_reference(prep, settings)
    seq_equal = ours == ref
    qv_equal = our_q == ref_q
    n_qv_diff = (sum(a != b for a, b in zip(our_q, ref_q))
                 if seq_equal else -1)
    print(json.dumps({
        "fixture": os.path.basename(FIXTURE),
        "n_mapped_reads": len(prep.mapped),
        "draft_len": len(prep.css),
        "consensus_len_ours": len(ours),
        "consensus_len_reference": len(ref),
        "consensus_identical": seq_equal,
        "qv_string_identical": qv_equal,
        "qv_positions_differing": n_qv_diff,
        "our_converged": res.converged,
        "reference_converged": stats.get("converged") == 1,
        "our_mean_qv_clamped": round(sum(ord(c) - 33 for c in our_q)
                                     / max(len(our_q), 1), 2),
        "ref_mean_qv_clamped": round(sum(ord(c) - 33 for c in ref_q)
                                     / max(len(ref_q), 1), 2),
    }))
    return 0 if seq_equal else 1


if __name__ == "__main__":
    sys.exit(main())
