#!/usr/bin/env python
"""Roofline-plane smoke for the tier-1 gate: CostCard determinism +
report + sentinel wiring.

Warms a 2-bucket menu TWICE through `ccs warmup` (fresh subprocess each
time; the persistent compile cache is SHARED so run 2 is cheap, but the
card stores are SEPARATE files so both runs extract fresh cards), then
asserts the properties the roofline attribution plane is trusted for:

  1. CARDS: every warmed bucket reports a CostCard (flops > 0) and the
     card store is written beside the compile cache;
  2. DETERMINISM: the two independently-extracted card stores are
     byte-identical -- XLA's cost model is a deterministic function of
     the bucket program, which is what makes flops/bytes honest
     "counter"-class ledger fields;
  3. REPORT: `ccs roofline --cards ... --format json` parses with one
     row per bucket (and the text renderer runs);
  4. SENTINEL: tools/perf_gate.py accepts a ledger carrying the new
     roofline_* fields, enforces the efficiency floor, and fails a
     perturbed-flops ledger with a structured diff naming the metric;
     obs.ledger rejects an undeclared roofline field (REG011-style).

The card store is copied to $ARTIFACTS_DIR (default
/tmp/ccs-perf-artifacts) for CI upload.

Usage:  JAX_PLATFORMS=cpu python tools/roofline_smoke.py
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# two buckets with distinct compiled shapes (Jmax 64 vs 128), small
# enough that the cold compile stays in tier-1 budget
BUCKETS = ("4x3x48", "4x3x100")


def run_warmup(tmp: str, cache: str, tag: str) -> tuple[dict, str]:
    """One fresh `ccs warmup` subprocess with its own card store;
    returns (report_doc, cards_path)."""
    cards = os.path.join(tmp, f"cards_{tag}.json")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PBCCS_ROOFLINE_CARDS=cards)
    env.pop("PBCCS_ROOFLINE", None)
    cmd = [sys.executable, "-m", "pbccs_tpu.cli", "warmup",
           "--compileCache", cache, "--logLevel", "WARN"]
    for b in BUCKETS:
        cmd += ["--bucket", b]
    t0 = time.monotonic()
    proc = subprocess.run(cmd, capture_output=True, text=True,
                          timeout=480, env=env, cwd=REPO)
    if proc.returncode != 0:
        raise AssertionError(f"warmup {tag} failed rc={proc.returncode}:"
                             f"\n{proc.stderr[-2000:]}")
    doc = json.loads(proc.stdout.strip().splitlines()[-1])
    print(f"roofline_smoke: warmup {tag} OK in "
          f"{time.monotonic() - t0:.1f}s")
    return doc, cards


def assert_cards(doc: dict, cards: str, tag: str) -> None:
    warmed = doc.get("warmed") or []
    assert len(warmed) == len(BUCKETS), doc
    for entry in warmed:
        card = entry.get("cost_card")
        assert card, f"warmup {tag}: bucket {entry.get('bucket')} has " \
                     f"no cost_card: {entry}"
        assert card["flops"] > 0, entry
        assert card["bytes_accessed"] > 0, entry
    assert doc.get("roofline_cards") == cards, doc
    with open(cards) as f:
        store = json.load(f)
    labels = sorted((store.get("cards") or {}))
    assert len(labels) == len(BUCKETS), \
        f"warmup {tag}: want {len(BUCKETS)} cards, got {labels}"
    print(f"roofline_smoke: cards {tag} OK ({', '.join(labels)})")


def run_gate(argv: list[str]) -> tuple[int, str]:
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "perf_gate.py")]
        + argv, capture_output=True, text=True, timeout=120)
    return proc.returncode, proc.stdout + proc.stderr


def check_sentinel(tmp: str) -> None:
    """Ledger schema + perf_gate wiring for the roofline fields, on a
    synthetic accelerator-platform ledger (floors are enforced only on
    matching accelerator platforms, so a CPU CI host still exercises
    the whole path)."""
    from pbccs_tpu.obs.ledger import LedgerSchemaError, PerfLedger

    led = PerfLedger(os.path.join(tmp, "schema_probe.ndjson"))
    try:
        led.append({"kind": "batch_run", "roofline_bogus": 1})
        raise AssertionError("ledger accepted an undeclared roofline "
                             "field")
    except LedgerSchemaError:
        pass
    led.append({"kind": "batch_run", "roofline_flops": 1,
                "roofline_bytes": 2, "roofline_achieved_tflops": 0.5,
                "roofline_efficiency": 0.01})
    print("roofline_smoke: ledger schema OK (declared fields accepted, "
          "undeclared rejected)")

    rec = {"schema_version": 1, "kind": "batch_run", "source": "smoke",
           "platform": "tpu", "jax_version": "smoke-jax", "zmws": 8,
           "roofline_flops": 1_000_000, "roofline_bytes": 2_000_000,
           "roofline_achieved_tflops": 2.0, "roofline_efficiency": 0.5}
    ledger = os.path.join(tmp, "roofline_ledger.ndjson")
    with open(ledger, "w") as f:
        f.write(json.dumps(rec) + "\n")
    baseline = os.path.join(tmp, "baseline.json")
    rc, out = run_gate([ledger, "--update-baseline",
                        "--baseline", baseline])
    assert rc == 0, f"baseline update failed:\n{out}"
    with open(baseline) as f:
        base = json.load(f)
    for field in ("roofline_flops", "roofline_bytes",
                  "roofline_achieved_tflops", "roofline_efficiency"):
        assert field in base["metrics"], base["metrics"]
    base["floors"] = {"roofline_efficiency": 0.1}
    with open(baseline, "w") as f:
        json.dump(base, f, indent=2, sort_keys=True)

    rc, out = run_gate([ledger, "--baseline", baseline])
    assert rc == 0, f"gate failed a clean roofline ledger:\n{out}"
    print("roofline_smoke: perf_gate OK on a clean roofline ledger "
          "(floor enforced, passing)")

    perturbed = dict(rec, roofline_flops=rec["roofline_flops"] + 12345)
    bad = os.path.join(tmp, "perturbed.ndjson")
    with open(bad, "w") as f:
        f.write(json.dumps(perturbed) + "\n")
    rc, out = run_gate([bad, "--counters-only", "--baseline", baseline])
    assert rc == 1, f"gate must fail perturbed roofline_flops:\n{out}"
    assert "roofline_flops" in out and "perf_gate_violation" in out, out

    slid = dict(rec, roofline_efficiency=0.05,
                roofline_achieved_tflops=0.2)
    bad2 = os.path.join(tmp, "slid.ndjson")
    with open(bad2, "w") as f:
        f.write(json.dumps(slid) + "\n")
    rc, out = run_gate([bad2, "--baseline", baseline])
    assert rc == 1, f"gate must fail an efficiency-floor slide:\n{out}"
    assert "roofline_efficiency" in out and '"floor"' in out, out
    print("roofline_smoke: perturbed ledgers correctly rejected "
          "(counter diff + efficiency floor)")


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    t0 = time.monotonic()
    tmp = tempfile.mkdtemp(prefix="pbccs_roofline_smoke_")
    try:
        cache = os.path.join(tmp, "compile_cache")
        doc_a, cards_a = run_warmup(tmp, cache, "a")
        assert_cards(doc_a, cards_a, "a")
        doc_b, cards_b = run_warmup(tmp, cache, "b")
        assert_cards(doc_b, cards_b, "b")

        blob_a = open(cards_a, "rb").read()
        blob_b = open(cards_b, "rb").read()
        assert blob_a == blob_b, (
            "CostCard stores from two fresh-process extractions differ "
            "-- the XLA cost model stopped being deterministic for the "
            "bucket program (diff the two JSON files)")
        print(f"roofline_smoke: determinism OK ({len(blob_a)} bytes "
              "byte-identical across fresh processes)")

        proc = subprocess.run(
            [sys.executable, "-m", "pbccs_tpu.cli", "roofline",
             "--cards", cards_a, "--format", "json"],
            capture_output=True, text=True, timeout=60, cwd=REPO,
            env=dict(os.environ, JAX_PLATFORMS="cpu"))
        assert proc.returncode == 0, proc.stderr[-2000:]
        report = json.loads(proc.stdout)
        assert report["source"] == "cards", report
        assert len(report["rows"]) == len(BUCKETS), report
        for row in report["rows"]:
            assert row["flops"] > 0, row
        proc = subprocess.run(
            [sys.executable, "-m", "pbccs_tpu.cli", "roofline",
             "--cards", cards_a],
            capture_output=True, text=True, timeout=60, cwd=REPO,
            env=dict(os.environ, JAX_PLATFORMS="cpu"))
        assert proc.returncode == 0 and "BUCKET" in proc.stdout, \
            proc.stdout + proc.stderr
        print("roofline_smoke: ccs roofline report OK (json + text)")

        check_sentinel(tmp)

        art_dir = os.environ.get("ARTIFACTS_DIR",
                                 "/tmp/ccs-perf-artifacts")
        os.makedirs(art_dir, exist_ok=True)
        shutil.copy(cards_a,
                    os.path.join(art_dir, "roofline_cards.json"))
        print(f"roofline_smoke: card artifact -> "
              f"{os.path.join(art_dir, 'roofline_cards.json')}")
        print(f"roofline_smoke: PASS in {time.monotonic() - t0:.1f}s")
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
