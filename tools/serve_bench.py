#!/usr/bin/env python
"""Load generator for `ccs serve`: latency/throughput vs the offline driver.

Generates a simulated multi-ZMW workload (simulate.simulate_zmw, the same
generator bench.py uses), then:

  1. OFFLINE BASELINE -- times pipeline.process_chunks over the whole
     workload in chunkSize batches (the batch CLI's execution shape) and
     records every consensus sequence;
  2. SERVING RUN -- starts a CcsEngine + CcsServer in-process (or targets
     --connect HOST:PORT), drives it with --clients concurrent sessions
     submitting the same ZMWs, and records per-request admission-to-result
     latency (client-side wall) and total throughput;
  3. CORRECTNESS -- every served Success must match the offline sequence
     for the same ZMW bit-for-bit (same chunks, same polish core);
  4. RESILIENCE PROBES (--no-chaos to skip) -- a client that disconnects
     mid-stream with requests in flight, a malformed frame, and a request
     that raises inside the engine (empty SNR): the server must keep
     answering afterwards.

Reports p50/p99 latency, ZMW/s for both drivers, and the final engine
status snapshot as JSON (stdout, plus --out FILE).

Usage:
    JAX_PLATFORMS=cpu python tools/serve_bench.py --zmws 32 --clients 4
"""

from __future__ import annotations

import argparse
import json
import socket
import sys
import threading
import time

import numpy as np

sys.path.insert(0, ".")  # runnable as tools/serve_bench.py from the repo root

from pbccs_tpu.pipeline import Chunk, ConsensusSettings, Subread, process_chunks
from pbccs_tpu.runtime.logging import Logger, LogLevel
from pbccs_tpu.serve.client import CcsClient, ServeError
from pbccs_tpu.serve.engine import CcsEngine, ServeConfig
from pbccs_tpu.serve.server import CcsServer
from pbccs_tpu.simulate import simulate_zmw


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--zmws", type=int, default=32)
    p.add_argument("--tplLen", type=int, default=120)
    p.add_argument("--passes", type=int, default=6)
    p.add_argument("--clients", type=int, default=4)
    p.add_argument("--maxBatch", type=int, default=8)
    p.add_argument("--maxWaitMs", type=float, default=500.0)
    p.add_argument("--maxPending", type=int, default=256)
    p.add_argument("--deadlineMs", type=float, default=600_000.0)
    p.add_argument("--chunkSize", type=int, default=64,
                   help="offline driver's ZMWs per batch")
    p.add_argument("--seed", type=int, default=20260803)
    p.add_argument("--connect", default=None, metavar="HOST:PORT",
                   help="target an external `ccs serve` instead of the "
                        "in-process engine")
    p.add_argument("--no-offline", action="store_true",
                   help="skip the offline baseline (and the correctness "
                        "diff against it)")
    p.add_argument("--no-chaos", action="store_true",
                   help="skip the resilience probes")
    p.add_argument("--out", default=None, help="also write the JSON here")
    return p


def make_workload(args) -> list[Chunk]:
    rng = np.random.default_rng(args.seed)
    chunks = []
    for i in range(args.zmws):
        _, reads, _, snr = simulate_zmw(rng, args.tplLen, args.passes)
        chunks.append(Chunk(
            f"bench/{i}",
            [Subread(f"bench/{i}/{k}", r) for k, r in enumerate(reads)],
            snr))
    return chunks


def run_offline(chunks, settings, chunk_size: int) -> tuple[float, dict]:
    t0 = time.monotonic()
    by_id: dict[str, str] = {}
    statuses: dict[str, int] = {}
    for lo in range(0, len(chunks), chunk_size):
        tally = process_chunks(chunks[lo: lo + chunk_size], settings)
        for r in tally.results:
            by_id[r.id] = r.sequence
        for f, c in tally.counts.items():
            statuses[f.value] = statuses.get(f.value, 0) + c
    return time.monotonic() - t0, {"sequences": by_id, "statuses": statuses}


def run_clients(host, port, chunks, n_clients, deadline_ms):
    """Drive the server with n_clients concurrent sessions; returns
    (wall_s, per-request latency ms list, replies by zmw id)."""
    shares = [chunks[i::n_clients] for i in range(n_clients)]
    latencies: list[float] = []
    replies: dict[str, dict] = {}
    errors: list[str] = []
    lock = threading.Lock()

    def one_client(share):
        with CcsClient(host, port) as cli:
            pending = []
            for chunk in share:
                t0 = time.monotonic()
                pending.append((chunk, t0, cli.submit_chunk(
                    chunk, deadline_ms=deadline_ms)))
            for chunk, t0, handle in pending:
                try:
                    msg = handle.reply(timeout=600.0)
                except (ServeError, ConnectionError, TimeoutError) as e:
                    with lock:
                        errors.append(f"{chunk.id}: {e}")
                    continue
                dt_ms = (time.monotonic() - t0) * 1e3
                with lock:
                    latencies.append(dt_ms)
                    replies[chunk.id] = msg

    threads = [threading.Thread(target=one_client, args=(s,))
               for s in shares if s]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return time.monotonic() - t0, latencies, replies, errors


def run_chaos(host, port) -> dict:
    """Server-resilience probes; returns what the server survived."""
    out = {}
    # 1. disconnect mid-stream: submits in flight, then slam the socket
    cli = CcsClient(host, port)
    cli.submit("chaos/disconnect", ["ACGTACGTACGTACGTACGT"] * 4)
    cli.close()
    out["disconnect_mid_stream"] = True
    # 2. malformed frame (session-level error, session stays open)
    raw = socket.create_connection((host, port), timeout=30.0)
    raw.sendall(b"this is not json\n")
    rf = raw.makefile("rb")
    reply = json.loads(rf.readline())
    out["malformed_frame_reply"] = reply.get("code")
    # same session must still answer
    raw.sendall(b'{"verb":"ping","id":"p1"}\n')
    out["session_survives_bad_frame"] = \
        json.loads(rf.readline()).get("type") == "pong"
    raw.close()
    # 3. a request that raises inside the engine: the in-process engine's
    # prep_fn is wrapped (main) to raise on this ZMW id, so the request
    # passes wire validation and fails INSIDE the engine -> structured
    # `internal` error, server stays up (an external --connect server has
    # no fault hook; the probe then just checks the reply is structured)
    with CcsClient(host, port) as cli2:
        handle = cli2.submit("chaos/raise", ["ACGTACGTACGTACGT"] * 4)
        try:
            msg = handle.reply(timeout=60.0)
            out["raising_request"] = msg.get("status", "no_error")
        except ServeError as e:
            out["raising_request"] = e.code
        # the engine and server must still serve AFTER the failure
        out["status_after_raise"] = cli2.status()["engine"] == "ccs-serve"
    return out


def pctl(xs, q):
    return float(np.percentile(np.asarray(xs), q)) if xs else float("nan")


def parse_prometheus(body: str) -> dict[str, float]:
    """Prometheus text -> {metric_with_labels: value} (comments dropped)."""
    out: dict[str, float] = {}
    for line in body.splitlines():
        if not line or line.startswith("#"):
            continue
        name, _, value = line.rpartition(" ")
        try:
            out[name] = float(value)
        except ValueError:
            continue
    return out


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    Logger.default(Logger(level=LogLevel.WARN))
    settings = ConsensusSettings()
    chunks = make_workload(args)

    report: dict = {
        "workload": {"zmws": args.zmws, "tpl_len": args.tplLen,
                     "passes": args.passes, "clients": args.clients,
                     "max_batch": args.maxBatch,
                     "max_wait_ms": args.maxWaitMs},
    }

    offline = None
    if not args.no_offline:
        offline_s, offline = run_offline(chunks, settings, args.chunkSize)
        report["offline"] = {
            "wall_s": round(offline_s, 3),
            "zmws_per_s": round(args.zmws / offline_s, 3),
            "statuses": offline["statuses"],
        }

    engine = server = None
    if args.connect:
        host, port_s = args.connect.rsplit(":", 1)
        host, port = host or "127.0.0.1", int(port_s)
    else:
        from pbccs_tpu.pipeline import prepare_chunk

        def prep_with_fault(chunk, settings):
            # chaos-probe fault injection: a request that raises INSIDE
            # the engine (everything else takes the real pipeline path)
            if chunk.id.startswith("chaos/raise"):
                raise RuntimeError("injected fault (serve_bench chaos)")
            return prepare_chunk(chunk, settings)

        engine = CcsEngine(settings, ServeConfig(
            max_batch=args.maxBatch, max_wait_ms=args.maxWaitMs,
            max_pending=args.maxPending,
            default_deadline_ms=args.deadlineMs),
            prep_fn=prep_with_fault).start()
        server = CcsServer(engine, port=0).start()
        host, port = server.host, server.port

    try:
        serve_s, lat, replies, errors = run_clients(
            host, port, chunks, args.clients, args.deadlineMs)
        statuses: dict[str, int] = {}
        for msg in replies.values():
            s = msg.get("status", "error")
            statuses[s] = statuses.get(s, 0) + 1
        report["serve"] = {
            "wall_s": round(serve_s, 3),
            "zmws_per_s": round(args.zmws / serve_s, 3),
            "latency_ms": {"p50": round(pctl(lat, 50), 1),
                           "p99": round(pctl(lat, 99), 1),
                           "max": round(max(lat), 1) if lat else None},
            "statuses": statuses,
            "client_errors": errors,
        }
        if offline is not None:
            match = sum(
                1 for zid, msg in replies.items()
                if msg.get("sequence") and
                msg["sequence"] == offline["sequences"].get(zid))
            served_success = sum(1 for m in replies.values()
                                 if m.get("sequence"))
            report["correctness"] = {
                "served_success": served_success,
                "offline_success": len(offline["sequences"]),
                "sequences_match_offline": match,
                "all_match": match == served_success ==
                len(offline["sequences"]),
            }
            off_rate = report["offline"]["zmws_per_s"]
            srv_rate = report["serve"]["zmws_per_s"]
            report["serve_vs_offline"] = round(srv_rate / off_rate, 3) \
                if off_rate else None

        if not args.no_chaos:
            report["chaos"] = run_chaos(host, port)
        with CcsClient(host, port) as cli:
            report["engine_status"] = cli.status(timeout=30.0)
            # end-of-run metrics snapshot (the Prometheus scrape the
            # `metrics` verb serves), parsed into name -> value so the
            # JSON report stays greppable
            report["metrics"] = parse_prometheus(cli.metrics(timeout=30.0))
    finally:
        if server is not None:
            server.shutdown()
        if engine is not None:
            engine.close()

    out = json.dumps(report, indent=2)
    print(out)
    if args.out:
        with open(args.out, "w") as f:
            f.write(out + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
