#!/usr/bin/env python
"""Endurance smoke for the tier-1 gate: a scaled-down full SMRT cell
streamed through the FLEET scheduler while every resource-exhaustion
failure class the governance layer handles is injected, asserting zero
lost ZMWs and output byte-identical to an unfaulted run.

The spec-scale endurance run (ROADMAP item 4, ~150k ZMWs) meets exactly
three failure classes a sustained run cannot avoid; this smoke scales
the cell down (~2 min budget on CPU) but injects all three against real
`ccs` subprocesses on a 2-virtual-device fleet:

  oom       sched.dispatch:oom -- a device OOM mid-stream: the memory
            governor must split the batch (never same-shape retry,
            never quarantine a healthy batch) and the run completes
  kill -9   SIGKILL after >= 2 journaled chunks: --resume restores the
            journal prefix and recomputes only the rest
  enospc    output.write:enospc~bam -- the disk fills while the BAM is
            written: a structured failure (exit 1, no torn output
            published, journal KEPT), then a final --resume once
            "space is freed" finishes byte-identically

The final BAM and CSV report must equal the unfaulted reference byte
for byte, and the yield total must account every input ZMW.

Usage:  JAX_PLATFORMS=cpu python tools/endurance_smoke.py
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, ".")  # runnable as tools/endurance_smoke.py

N_ZMWS = 24
TPL_LEN = 60
N_PASSES = 5
CHUNK = 4          # -> 6 chunks: several journal records + dispatches
DEVICES = 2
SEED = 20260804

_CHILD_ENV = dict(
    os.environ,
    JAX_PLATFORMS="cpu",
    # the host refinement loop keeps the compile budget sane on CPU
    # (parity-pinned against the device loop in test_device_refine)
    PBCCS_DEVICE_REFINE="0",
)
_flags = _CHILD_ENV.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    _CHILD_ENV["XLA_FLAGS"] = (
        _flags + f" --xla_force_host_platform_device_count={DEVICES}"
    ).strip()


def check(name: str, ok: bool, detail: str = "") -> None:
    print(f"  {'PASS' if ok else 'FAIL'}  {name}"
          + (f"  ({detail})" if detail else ""))
    if not ok:
        raise SystemExit(f"endurance smoke failed: {name} {detail}")


def write_workload(path: str) -> None:
    import numpy as np

    from pbccs_tpu.models.arrow.params import decode_bases
    from pbccs_tpu.simulate import simulate_zmw

    rng = np.random.default_rng(SEED)
    with open(path, "w") as f:
        for i in range(N_ZMWS):
            _, reads, _, _snr = simulate_zmw(rng, TPL_LEN, N_PASSES)
            for k, r in enumerate(reads):
                f.write(f">cell/{i}/{k}_{k + 1}\n{decode_bases(r)}\n")


def cli_cmd(out: str, fasta: str, extra: tuple = ()) -> list[str]:
    return [sys.executable, "-m", "pbccs_tpu.cli",
            "--skipChemistryCheck", "--chunkSize", str(CHUNK),
            "--devices", str(DEVICES), "--memBudget", "1G",
            "--reportFile", out + ".csv", *extra, out, fasta]


def run_cli(cmd: list[str], timeout: float = 600.0):
    return subprocess.run(cmd, env=_CHILD_ENV, capture_output=True,
                          text=True, timeout=timeout)


def journal_chunks(path: str) -> int:
    if not os.path.exists(path):
        return 0
    n = 0
    with open(path, "rb") as f:
        for line in f:
            try:
                n += json.loads(line).get("type") == "chunk"
            except ValueError:
                pass
    return n


def read_csv_total(path: str) -> int:
    total = 0
    with open(path) as f:
        for line in f:
            parts = line.strip().split(",")
            if len(parts) == 3:
                total += int(parts[1])
    return total


def main() -> int:
    t_start = time.monotonic()
    tmp = tempfile.mkdtemp(prefix="pbccs_endurance_")
    fasta = os.path.join(tmp, "cell.fasta")
    write_workload(fasta)

    try:
        print("== phase 0: unfaulted reference (fleet, streamed) ==")
        ref = os.path.join(tmp, "ref.bam")
        r = run_cli(cli_cmd(ref, fasta))
        check("reference run ok", r.returncode == 0,
              r.stderr[-300:] if r.returncode else "")
        ref_total = read_csv_total(ref + ".csv")
        check("reference accounts every ZMW", ref_total == N_ZMWS,
              f"{ref_total}/{N_ZMWS}")

        print("== phase 1: kill -9 mid-stream (checkpoint armed) ==")
        out = os.path.join(tmp, "out.bam")
        ckpt = os.path.join(tmp, "cell.ckpt")
        # a per-dispatch delay keeps the warm-cache run slow enough for
        # the journal poll to catch it mid-stream (results unchanged)
        proc = subprocess.Popen(
            cli_cmd(out, fasta, ("--checkpoint", ckpt, "--faults",
                                 "sched.dispatch:delay=0.4")),
            env=_CHILD_ENV, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL)
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline and proc.poll() is None:
            if journal_chunks(ckpt) >= 2:
                break
            time.sleep(0.1)
        journaled = journal_chunks(ckpt)
        if proc.poll() is None:
            os.kill(proc.pid, signal.SIGKILL)
            proc.wait(30)
        check("killed with >= 2 journaled chunks", journaled >= 2,
              f"{journaled} journaled")
        check("kill was mid-run", proc.returncode != 0,
              f"exit {proc.returncode}")
        check("no torn output published", not os.path.exists(out))

        print("== phase 2: resume + injected OOM + disk-full BAM ==")
        # the resumed run recomputes the unjournaled chunks: the FIRST
        # fleet dispatch OOMs (governor split, same device), and once
        # every chunk is journaled the BAM writer hits a "full disk"
        r = run_cli(cli_cmd(out, fasta, (
            "--checkpoint", ckpt, "--resume", "--faults",
            "sched.dispatch:oom@1*1,output.write:enospc~bam@1*1")))
        check("disk-full run exits nonzero", r.returncode == 1,
              f"exit {r.returncode}: {r.stderr[-300:]}")
        check("oom handled by governor split",
              "memory governor: capacity failure" in r.stderr
              and "governor-split re-dispatch" in r.stderr)
        check("no healthy batch quarantined",
              "quarantined" not in r.stderr)
        check("disk-full failure is structured",
              "free disk space" in r.stderr and "bam write" in r.stderr)
        check("no torn BAM published", not os.path.exists(out))
        check("no temp file leaked", not os.path.exists(out + ".tmp"))
        check("journal survives the disk-full failure",
              journal_chunks(ckpt) >= journaled)

        print("== phase 3: space freed -> final resume ==")
        r = run_cli(cli_cmd(out, fasta, ("--checkpoint", ckpt,
                                         "--resume")))
        check("final resume ok", r.returncode == 0,
              r.stderr[-300:] if r.returncode else "")
        check("resume restored journaled chunks",
              "restored" in r.stderr and "completed chunk" in r.stderr)
        check("journal removed after success", not os.path.exists(ckpt))

        print("== verdict: zero loss, byte-identity ==")
        with open(ref, "rb") as a, open(out, "rb") as b:
            check("BAM byte-identical to unfaulted run",
                  a.read() == b.read())
        check("report byte-identical to unfaulted run",
              open(ref + ".csv").read() == open(out + ".csv").read())
        out_total = read_csv_total(out + ".csv")
        check("zero lost ZMWs", out_total == N_ZMWS,
              f"{out_total}/{N_ZMWS}")
    finally:
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)

    dt = time.monotonic() - t_start
    print(f"endurance smoke: all checks passed in {dt:.1f}s "
          f"(budget 120s scaled run)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
