#!/usr/bin/env python
"""Noise-aware performance-regression sentinel over perf-ledger records.

Compares a fresh run's ledger (pbccs_tpu/obs/ledger.py NDJSON) against
the committed PERF_BASELINE.json with PER-METRIC-CLASS tolerances, so
the gate is strict exactly where determinism makes strictness honest:

  counter   CPU-deterministic counts (polish dispatches, refine rounds,
            slot totals, governor interventions): exact match,
            enforced EVERYWHERE -- a drifted counter is a behavior
            change, not noise;
  ratio     CPU-deterministic ratios/shares (fill ratio, padding waste,
            kernel_fraction, span-rollup region shares): absolute band
            (default 0.02), enforced everywhere;
  compile   compile/cache counts: exact, but only when the ledger's
            jax_version matches the baseline's (a jax upgrade
            legitimately changes compile behavior -- the mismatch is
            printed as a note, never a silent pass);
  wall      wall-clock figures (wall_s, zmws_per_sec, device waits):
            MEDIAN across the ledger's matching records vs a relative
            band (default 35%), enforced only when the observed
            platform matches the baseline's AND is not "cpu" --
            CPU wall time in CI is noise, accelerator wall time is the
            product;
  resource  peak RSS: median vs a wide relative band (default 50%),
            same platform rule as wall.

An optional baseline ``floors`` section maps ledger fields to hard
MINIMUMS (violation class "floor"): the roofline efficiency floor for
the headline config lives here, so a kernel-share slide is caught by
the sentinel even when the relative wall band would tolerate it.
Floors follow their field's class gating (wall-class floors only on a
matching accelerator platform; none in --counters-only mode) and are
carried through --update-baseline verbatim -- they are policy, not
measurement.  A floor field absent from the selector-matched records
is read from the latest record of any kind in the ledger (fields like
``tenant_b_p99_gain`` ride ``tenant_snapshot`` rows, not the
``batch_run`` rows the class bands select).

Exit 0 clean; exit 1 with ONE structured JSON diff line per violation
(metric, class, baseline, observed, tolerance); exit 2 on usage errors
(no ledger, no matching records, bad baseline).

``--update-baseline`` rewrites PERF_BASELINE.json from the observed
ledger and REFUSES to loosen silently: every accepted change is printed
as `perf_gate: accepting <metric>: <old> -> <new>` before the write.

Usage:
    python tools/perf_gate.py LEDGER.ndjson
    python tools/perf_gate.py LEDGER.ndjson --counters-only
    python tools/perf_gate.py LEDGER.ndjson --update-baseline
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
from typing import Any

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pbccs_tpu.obs.ledger import LEDGER_FIELDS  # noqa: E402

DEFAULT_BASELINE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "PERF_BASELINE.json")

BASELINE_VERSION = 1

DEFAULT_TOLERANCES = {
    "counter": 0.0,    # allowed absolute count difference
    "ratio": 0.02,     # allowed absolute ratio difference
    "compile": 0.0,    # allowed absolute count difference (same jax)
    "wall": 0.35,      # allowed relative regression
    "resource": 0.5,   # allowed relative regression
}

# wall/resource metrics regress in a direction; improvements never fail
_LOWER_IS_BETTER = {"wall_s", "device_wait_s", "device_step_ms",
                    "compile_s", "peak_rss_bytes"}

# classes the gate may enforce (meta/live are recorded, never gated)
_GATED = ("counter", "ratio", "compile", "wall", "resource")


def _select_records(records: list[dict], select: dict) -> list[dict]:
    out = []
    for rec in records:
        if all(rec.get(k) == v for k, v in select.items()):
            out.append(rec)
    return out


def _numeric(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def observed_metrics(records: list[dict]) -> dict[str, Any]:
    """Collapse matching records into one observed-metric map: the LAST
    record for deterministic classes, the MEDIAN across records for the
    noisy wall/resource classes (median-of-N is the committed
    statistic, mirroring bench.py's repeat handling)."""
    out: dict[str, Any] = {}
    last = records[-1]
    for field, cls in LEDGER_FIELDS.items():
        if cls in ("counter", "ratio", "compile"):
            if field == "region_shares":
                if isinstance(last.get(field), dict):
                    out[field] = last[field]
            elif _numeric(last.get(field)):
                out[field] = last[field]
        elif cls in ("wall", "resource"):
            vals = [r[field] for r in records if _numeric(r.get(field))]
            if vals:
                out[field] = statistics.median(vals)
    return out


def bad_baseline_reason(baseline: dict) -> str | None:
    """Why this baseline document is unusable (None = fine): a corrupt
    or hand-mangled baseline must be a clean exit-2 diagnostic, never a
    TypeError traceback mid-compare."""
    metrics = baseline.get("metrics")
    if not isinstance(metrics, dict):
        return "metrics must be an object"
    for name, val in metrics.items():
        if name == "region_shares":
            if not (isinstance(val, dict)
                    and all(_numeric(v) for v in val.values())):
                return "metrics.region_shares must be an object of numbers"
        elif not _numeric(val):
            return (f"metrics.{name} must be a number, got "
                    f"{type(val).__name__}")
    tolerances = baseline.get("tolerances")
    if tolerances is not None:
        if not isinstance(tolerances, dict):
            return "tolerances must be an object"
        for cls, tol in tolerances.items():
            if not _numeric(tol):
                return (f"tolerances.{cls} must be a number, got "
                        f"{type(tol).__name__}")
    select = baseline.get("select")
    if select is not None and not isinstance(select, dict):
        return "select must be an object"
    floors = baseline.get("floors")
    if floors is not None:
        if not isinstance(floors, dict):
            return "floors must be an object"
        for name, val in floors.items():
            if not _numeric(val):
                return (f"floors.{name} must be a number, got "
                        f"{type(val).__name__}")
            if LEDGER_FIELDS.get(name) not in _GATED:
                return (f"floors.{name}: not a gated ledger field")
    return None


def _violation(metric: str, cls: str, base, obs, tol) -> dict:
    return {"metric": metric, "class": cls, "baseline": base,
            "observed": obs, "tolerance": tol}


def compare(baseline: dict, records: list[dict], *,
            counters_only: bool = False,
            all_records: list[dict] | None = None,
            ignore: set[str] | frozenset[str] | None = None
            ) -> tuple[list[dict], list[str]]:
    """(violations, notes) of the observed ledger records vs baseline.

    `records` are the selector-matched records the class bands run
    over; `all_records` (default: same) is the whole ledger, which
    floors may fall back to for fields only specialized record kinds
    carry (e.g. tenant_snapshot's tenant_b_p99_gain).  `ignore` names
    metrics exempt from enforcement (noted, not silently dropped) --
    the ccs-tune referee uses it for fields a candidate knob
    legitimately perturbs (e.g. band_w changes compile counts)."""
    tol = {**DEFAULT_TOLERANCES, **(baseline.get("tolerances") or {})}
    base_metrics = baseline.get("metrics") or {}
    obs = observed_metrics(records)
    last = records[-1]
    notes: list[str] = []
    violations: list[dict] = []
    if ignore:
        exempt = sorted(set(ignore) & set(base_metrics))
        if exempt:
            base_metrics = {k: v for k, v in base_metrics.items()
                            if k not in ignore}
            notes.append("metrics exempted by --ignore: "
                         + ", ".join(exempt))

    jax_match = (last.get("jax_version") == baseline.get("jax_version"))
    platform = last.get("platform")
    wall_enforced = (not counters_only
                     and platform == baseline.get("platform")
                     and platform not in (None, "cpu"))
    if not jax_match:
        notes.append(
            f"compile-class metrics skipped: ledger jax_version "
            f"{last.get('jax_version')!r} != baseline "
            f"{baseline.get('jax_version')!r}")
    if not wall_enforced and not counters_only:
        notes.append(
            f"wall/resource classes skipped on platform {platform!r} "
            f"(baseline platform {baseline.get('platform')!r}; "
            "wall-clock is enforced on matching accelerator hosts only)")

    for metric, base_val in sorted(base_metrics.items()):
        cls = LEDGER_FIELDS.get(metric)
        if cls not in _GATED:
            notes.append(f"baseline metric {metric!r} has no gated "
                         "class; ignored")
            continue
        if cls == "compile" and not jax_match:
            continue
        if cls in ("wall", "resource") and not wall_enforced:
            continue
        obs_val = obs.get(metric)
        if metric == "region_shares":
            base_shares = base_val if isinstance(base_val, dict) else {}
            obs_shares = obs_val if isinstance(obs_val, dict) else {}
            for region in sorted(set(base_shares) | set(obs_shares)):
                b = float(base_shares.get(region, 0.0))
                o = float(obs_shares.get(region, 0.0))
                if abs(o - b) > tol["ratio"]:
                    violations.append(_violation(
                        f"region_shares.{region}", "ratio", b, o,
                        tol["ratio"]))
            continue
        if not _numeric(base_val):
            # defense in depth for library callers that skipped the
            # bad_baseline_reason gate; main() exits 2 before this
            notes.append(f"baseline metric {metric!r} is non-numeric; "
                         "skipped")
            continue
        if obs_val is None:
            violations.append(_violation(
                metric, cls, base_val, None, tol[cls]))
            continue
        if cls in ("counter", "compile", "ratio"):
            if abs(obs_val - base_val) > tol[cls]:
                violations.append(_violation(metric, cls, base_val,
                                             obs_val, tol[cls]))
        else:  # wall / resource: relative band, regression direction only
            if base_val == 0:
                continue
            if metric in _LOWER_IS_BETTER:
                rel = (obs_val - base_val) / base_val
            else:
                rel = (base_val - obs_val) / base_val
            if rel > tol[cls]:
                violations.append(_violation(metric, cls, base_val,
                                             round(obs_val, 4),
                                             tol[cls]))

    # floors: hard minimums (e.g. roofline_efficiency for the headline
    # config) -- a kernel-share slide fails here even when the relative
    # band above would tolerate it.  Enforcement gating mirrors the
    # floor field's class: wall/resource floors only on a matching
    # accelerator platform, compile floors only on a matching jax, and
    # none of them in --counters-only mode.
    floors = baseline.get("floors") or {}
    for metric, floor in sorted(floors.items()):
        cls = LEDGER_FIELDS.get(metric)
        if cls not in _GATED or not _numeric(floor):
            continue
        if counters_only:
            notes.append(f"floor {metric!r} skipped in counters-only "
                         "mode")
            continue
        if cls == "compile" and not jax_match:
            continue
        if cls in ("wall", "resource") and not wall_enforced:
            notes.append(f"floor {metric!r} skipped on platform "
                         f"{platform!r}")
            continue
        obs_val = obs.get(metric)
        if obs_val is None:
            # a floor may target a field only a specialized record kind
            # carries (tenant_snapshot's tenant_b_p99_gain): fall back
            # to the latest record of ANY kind in the ledger with it
            obs_val = next(
                (r[metric] for r in reversed(all_records or records)
                 if _numeric(r.get(metric))), None)
        if not _numeric(obs_val) or obs_val < floor:
            violations.append(_violation(metric, "floor", floor,
                                         obs_val, 0.0))
    return violations, notes


def build_baseline(records: list[dict], select: dict,
                   tolerances: dict | None = None) -> dict:
    """A fresh baseline document from observed records."""
    last = records[-1]
    return {
        "baseline_version": BASELINE_VERSION,
        "select": select,
        "jax_version": last.get("jax_version"),
        "platform": last.get("platform"),
        "tolerances": {**DEFAULT_TOLERANCES, **(tolerances or {})},
        "metrics": observed_metrics(records),
    }


def update_baseline(path: str, baseline: dict | None,
                    records: list[dict], select: dict) -> dict:
    """--update-baseline: rewrite `path` from the observed ledger,
    printing every accepted change (never a silent loosening).  A
    corrupt old baseline is replaced wholesale (its unusable sections
    are ignored, not crashed on)."""
    old_metrics = (baseline or {}).get("metrics")
    if not isinstance(old_metrics, dict):
        old_metrics = {}
    old_tol = (baseline or {}).get("tolerances")
    fresh = build_baseline(records, select,
                           old_tol if isinstance(old_tol, dict)
                           and all(_numeric(v) for v in old_tol.values())
                           else None)
    # floors are policy, not measurement: carry them through verbatim
    # (a refresh must not silently drop the efficiency floor)
    old_floors = (baseline or {}).get("floors")
    if isinstance(old_floors, dict) and old_floors \
            and all(_numeric(v) for v in old_floors.values()):
        fresh["floors"] = old_floors
    for metric in sorted(set(old_metrics) | set(fresh["metrics"])):
        old, new = old_metrics.get(metric), fresh["metrics"].get(metric)
        if old != new:
            print(f"perf_gate: accepting {metric}: {old} -> {new}")
    if baseline is not None \
            and baseline.get("jax_version") != fresh.get("jax_version"):
        print(f"perf_gate: accepting jax_version: "
              f"{baseline.get('jax_version')} -> "
              f"{fresh.get('jax_version')}")
    from pbccs_tpu.resilience.resources import atomic_output

    with atomic_output(path, "perf_baseline") as fh:
        json.dump(fresh, fh, indent=2, sort_keys=True)
        fh.write("\n")
    print(f"perf_gate: baseline written to {path}")
    return fresh


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="perf_gate",
        description="Gate a perf-ledger against PERF_BASELINE.json with "
                    "noise-aware per-metric-class tolerances.")
    p.add_argument("ledger", help="Perf-ledger NDJSON path.")
    p.add_argument("--baseline", default=DEFAULT_BASELINE,
                   help="Baseline JSON. Default = %(default)s")
    p.add_argument("--counters-only", action="store_true",
                   help="Enforce only the CPU-deterministic classes "
                        "(counter/ratio/compile); the tier-1 CI mode.")
    p.add_argument("--ignore", action="append", default=[],
                   metavar="METRIC",
                   help="Exempt a metric from enforcement (repeatable; "
                        "noted on stderr, never silent). The ccs-tune "
                        "referee's escape hatch for fields a candidate "
                        "knob legitimately perturbs.")
    p.add_argument("--kind", default=None,
                   help="Override the baseline's record-kind selector.")
    p.add_argument("--source", default=None,
                   help="Override the baseline's record-source selector.")
    p.add_argument("--update-baseline", action="store_true",
                   help="Rewrite the baseline from this ledger, printing "
                        "every accepted delta (no silent loosening).")
    args = p.parse_args(argv)

    from pbccs_tpu.obs.ledger import read_ledger

    records, skipped = read_ledger(args.ledger)
    if skipped:
        print(f"perf_gate: note: {skipped} unparseable ledger line(s) "
              "skipped (torn tail?)", file=sys.stderr)
    if not records:
        print(f"perf_gate: no records in {args.ledger}", file=sys.stderr)
        return 2

    baseline = None
    if os.path.exists(args.baseline):
        try:
            with open(args.baseline) as fh:
                baseline = json.load(fh)
        except (OSError, json.JSONDecodeError) as e:
            print(f"perf_gate: bad baseline {args.baseline}: {e}",
                  file=sys.stderr)
            return 2
        if not isinstance(baseline, dict):
            print(f"perf_gate: bad baseline {args.baseline}: not a "
                  "JSON object", file=sys.stderr)
            return 2
        reason = bad_baseline_reason(baseline)
        if reason is not None and not args.update_baseline:
            print(f"perf_gate: bad baseline {args.baseline}: {reason}",
                  file=sys.stderr)
            return 2

    raw_select = (baseline or {}).get("select")
    select = (dict(raw_select) if isinstance(raw_select, dict)
              and raw_select else {"kind": "batch_run"})
    if args.kind:
        select["kind"] = args.kind
    if args.source:
        select["source"] = args.source
    matching = _select_records(records, select)
    if not matching:
        print(f"perf_gate: no ledger records match selector {select} "
              f"({len(records)} record(s) total)", file=sys.stderr)
        return 2

    if args.update_baseline:
        update_baseline(args.baseline, baseline, matching, select)
        return 0

    if baseline is None:
        print(f"perf_gate: no baseline at {args.baseline}; run with "
              "--update-baseline to create one", file=sys.stderr)
        return 2

    violations, notes = compare(baseline, matching,
                                counters_only=args.counters_only,
                                all_records=records,
                                ignore=set(args.ignore) or None)
    for note in notes:
        print(f"perf_gate: note: {note}", file=sys.stderr)
    if violations:
        for v in violations:
            print(json.dumps({"perf_gate_violation": v},
                             sort_keys=True))
        print(f"perf_gate: FAIL: {len(violations)} regression(s) vs "
              f"{args.baseline} over {len(matching)} record(s)",
              file=sys.stderr)
        return 1
    print(f"perf_gate: OK: {len(matching)} record(s) within tolerance "
          f"of {args.baseline}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
