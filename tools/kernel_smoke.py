#!/usr/bin/env python
"""Tier-1 kernel gate: the dense interior + edge mutation-scoring kernels
(ops/dense_score_pallas, interpret mode on CPU) against the float64 DENSE
oracle (ops/fwdbwd_ref) on one fixed seed, under a ~30 s budget.

Regime: band width W >= I + 1, so the banded kernel covers the whole DP
matrix and its absolute mutated-window log-likelihood must equal
`loglik_dense` of the mutated window to f32 rounding -- a ground-truth
check, not a same-code parity check.  Also pins the pre-baked layout
path (prepare_dense_layout) BITWISE against the in-graph derivation, so
a prepare-time layout bug cannot pass the gate by matching itself.

Deterministic: seed 20260729, no environment dependence beyond
JAX_PLATFORMS=cpu (tier1.sh sets it)."""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

W = 24          # band >= I + 1 for every read below (dense-cover regime)
L = 14          # window template length
SEED = 20260729


def main() -> int:
    t0 = time.perf_counter()
    import jax
    import jax.numpy as jnp
    import numpy as np

    from pbccs_tpu.models.arrow import mutations as mutlib
    from pbccs_tpu.models.arrow.params import (
        snr_to_transition_table_host,
        revcomp_padded,
        template_transition_params,
    )
    from pbccs_tpu.models.arrow.scorer import (fill_alpha_beta_batch,
                                               oriented_window)
    from pbccs_tpu.ops import dense_score_pallas as dsp
    from pbccs_tpu.ops.fwdbwd_ref import loglik_dense
    from pbccs_tpu.simulate import simulate_zmw

    rng = np.random.default_rng(SEED)
    tpl, reads, strands, snr = simulate_zmw(rng, L, 2)
    Jmax = 64
    Imax = Jmax + 32
    table = jnp.asarray(snr_to_transition_table_host(np.asarray(snr)))
    tpl_p = jnp.asarray(np.pad(tpl, (0, Jmax - L), constant_values=4))
    tlen = jnp.int32(L)
    tpl_r = revcomp_padded(tpl_p, tlen)

    windows = [(0, 0, L), (1, 0, L)]
    R = len(windows)
    reads_p = np.full((R, Imax), 4, np.int8)
    rlens = np.zeros(R, np.int32)
    st = np.zeros(R, np.int32)
    ts_a = np.zeros(R, np.int32)
    te_a = np.zeros(R, np.int32)
    for i, (strand, ts, te) in enumerate(windows):
        r = np.asarray(reads[i])[: W - 2]   # dense-cover: I <= W - 2
        reads_p[i, : len(r)] = r
        rlens[i] = len(r)
        st[i], ts_a[i], te_a[i] = strand, ts, te

    win_tpl, win_trans, wlens = jax.vmap(
        lambda s, a, b: oriented_window(s, a, b, tpl_p, tpl_r, tlen, table)
    )(jnp.asarray(st), jnp.asarray(ts_a), jnp.asarray(te_a))
    alpha, beta, _, _, apre, bsuf = fill_alpha_beta_batch(
        jnp.asarray(reads_p), jnp.asarray(rlens), win_tpl, win_trans,
        wlens, W, use_pallas=False)
    tables = jnp.broadcast_to(table[None], (R, 8, 4))
    args = (jnp.asarray(reads_p), jnp.asarray(rlens), win_tpl, win_trans,
            wlens, tables, alpha, beta, apre, bsuf, W)

    # the PRE-BAKED layout path end to end (prepare_dense_layout ->
    # kernels): matching the f64 oracle pins kernels AND baked buffers
    # in one pass.  (Bitwise prebaked==in-graph equivalence is pinned by
    # tests/test_dense_score.py::test_prepared_layout_matches_ingraph in
    # the tier-1 suite; re-deriving it here would double the trace count
    # and blow the budget.)
    layout = dsp.prepare_dense_layout(*args)
    grid = np.asarray(dsp.dense_interior_scores_batch(*args, layout=layout))
    edge_args = (jnp.asarray(reads_p), jnp.asarray(rlens), win_tpl,
                 win_trans, wlens, alpha, beta, apre, bsuf)
    e6 = np.asarray(dsp.edge_window_scores_batch(
        *edge_args, None, W, layout=layout))

    # f64 dense oracle over every served slot of every read
    slot_mt = [0, 0, 0, 0, 1, 1, 1, 1, 2]
    slot_nb = [0, 1, 2, 3, 0, 1, 2, 3, -1]
    n_checked = 0
    worst = 0.0
    for r in range(R):
        J = int(wlens[r])
        I = int(rlens[r])
        assert W >= I + 1, "smoke regime needs a full-cover band"
        wt = np.asarray(win_tpl[r])[:J].astype(np.int8)
        read = reads_p[r, :I].astype(np.int8)

        def oracle(p, k):
            mtype, nbase = slot_mt[k], slot_nb[k]
            end = p + (0 if mtype == 1 else 1)
            mut = mutlib.Mutation(start=p, end=end, mtype=mtype,
                                  new_base=max(nbase, 0))
            mtpl = mutlib.apply_mutations(wt, [mut])
            mtr = np.asarray(template_transition_params(
                jnp.asarray(mtpl.astype(np.int32)), table,
                jnp.int32(len(mtpl))), np.float64)[: len(mtpl)]
            return loglik_dense(read, mtpl, mtr)

        def check(got, p, k, where):
            nonlocal n_checked, worst
            want = oracle(p, k)
            err = abs(got - want) / max(abs(want), 1.0)
            worst = max(worst, err)
            assert err < 5e-4, \
                f"{where} r={r} p={p} k={k}: got {got} want {want}"
            n_checked += 1

        # interior slots (kernel scope: p >= 3, end <= J - 2)
        for p in range(3, J - 2):
            for k in range(9):
                if slot_mt[k] != 1 and p + 1 > J - 2:
                    continue
                check(float(grid[r, p, k]), p, k, "interior")
        # edge rows {0,1,2} x {J-2,J-1,J}, regime rules as splice_edge_rows
        for row, p in enumerate([0, 1, 2, J - 2, J - 1, J]):
            for k in range(9):
                mtype = slot_mt[k]
                if mtype == 1:
                    if p > J or row == 3:
                        continue
                elif p >= J:
                    continue
                if p <= 2 and row >= 3:
                    continue
                check(float(e6[r, row, k]), p, k, "edge")

    dt = time.perf_counter() - t0
    assert n_checked > 150, f"too few oracle checks ({n_checked})"
    print(f"kernel smoke OK: {n_checked} slots (prebaked-layout path) "
          f"vs f64 dense oracle, worst rel err {worst:.2e}, {dt:.1f}s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
