#!/usr/bin/env python
"""Capture a jax.profiler trace of one full polish and attribute device time
per HLO op via xprof's hlo_stats converter (no TensorBoard UI needed).

Usage:
  python tools/trace_polish.py [outdir]          # capture + parse
  PBCCS_TRACE_PARSE_ONLY=1 python tools/trace_polish.py [outdir]  # parse only

Env: BENCH_ZMWS/BENCH_TPL_LEN/BENCH_PASSES/BENCH_CORRUPTIONS as bench.py.
Prints a category rollup and the top ops by device self-time, plus one JSON
summary line (committed to docs/PROFILE_r03.md by hand).
"""

from __future__ import annotations

import collections
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def capture(outdir: str):
    import numpy as np

    from pbccs_tpu.runtime.cache import enable_compilation_cache

    enable_compilation_cache()

    import jax

    from bench import build_tasks
    from pbccs_tpu.models.arrow.refine import RefineOptions
    from pbccs_tpu.parallel.batch import BatchPolisher

    Z = int(os.environ.get("BENCH_ZMWS", 128))
    L = int(os.environ.get("BENCH_TPL_LEN", 300))
    P = int(os.environ.get("BENCH_PASSES", 8))
    NC = int(os.environ.get("BENCH_CORRUPTIONS", 2))

    def run():
        tasks = build_tasks(np.random.default_rng(20260729), Z, L, P, NC)[0]
        p = BatchPolisher(tasks)
        p.refine(RefineOptions(max_iterations=10))
        p.consensus_qvs()

    run()  # warmup: compile everything
    with jax.profiler.trace(outdir):
        run()


def parse(outdir: str):
    from xprof.convert import raw_to_tool_data as r

    paths = glob.glob(os.path.join(outdir, "**", "*.xplane.pb"),
                      recursive=True)
    assert paths, f"no xplane.pb under {outdir}"
    paths = [max(paths, key=os.path.getmtime)]
    data, _ = r.xspace_to_tool_data(paths, "hlo_stats", {})
    table = json.loads(data if isinstance(data, str) else data.decode())
    cols = [c["id"] for c in table["cols"]]
    idx = {c: i for i, c in enumerate(cols)}
    rows = []
    for row in table["rows"]:
        v = [c.get("v") for c in row["c"]]
        rows.append({
            "category": v[idx["category"]],
            "name": v[idx["hlo_op_name"]],
            "expr": v[idx["hlo_op_expression"]] or "",
            "frame_op": v[idx["tf_op_name"]] or "",
            "occurrences": v[idx["occurrences"]] or 0,
            "self_us": v[idx["total_self_time"]] or 0.0,
        })
    return paths[0], rows


def main():
    outdir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/pbccs_trace"
    if not os.environ.get("PBCCS_TRACE_PARSE_ONLY"):
        capture(outdir)
    path, rows = parse(outdir)
    total = sum(r["self_us"] for r in rows)
    per_cat = collections.defaultdict(float)
    for r in rows:
        per_cat[r["category"]] += r["self_us"]
    print(f"# parsed {path}", file=sys.stderr)
    print(f"# total device self time: {total / 1e3:.1f} ms", file=sys.stderr)
    print("\n== category rollup (ms, % of device) ==", file=sys.stderr)
    rollup = sorted(per_cat.items(), key=lambda kv: -kv[1])
    for cat, us in rollup:
        print(f"{cat:28s} {us / 1e3:10.1f}  {100 * us / total:5.1f}%",
              file=sys.stderr)
    print("\n== top ops by self time (ms | % | occurrences) ==",
          file=sys.stderr)
    ops = sorted(rows, key=lambda r: -r["self_us"])[:40]
    for r in ops:
        label = r["frame_op"] or r["name"]
        print(f"{r['self_us'] / 1e3:9.2f} {100 * r['self_us'] / total:5.1f}% "
              f"x{r['occurrences']:<6} {r['category']:16s} {label[:90]}",
              file=sys.stderr)
    print(json.dumps({
        "total_device_ms": round(total / 1e3, 1),
        "categories": {k: round(v / 1e3, 1) for k, v in rollup},
        "top_ops": [{"name": (r["frame_op"] or r["name"])[:160],
                     "category": r["category"],
                     "ms": round(r["self_us"] / 1e3, 2),
                     "n": r["occurrences"]} for r in ops[:15]],
    }))


if __name__ == "__main__":
    main()
