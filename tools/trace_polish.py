#!/usr/bin/env python
"""Capture a jax.profiler trace of one full polish and attribute device time
per HLO op via xprof's hlo_stats converter (no TensorBoard UI needed).

Usage:
  python tools/trace_polish.py [outdir]          # capture + parse
  PBCCS_TRACE_PARSE_ONLY=1 python tools/trace_polish.py [outdir]  # parse only

Env: BENCH_ZMWS/BENCH_TPL_LEN/BENCH_PASSES/BENCH_CORRUPTIONS as bench.py.
Prints a category rollup and the top ops by device self-time, plus one JSON
summary line (committed to docs/PROFILE_r03.md by hand).
"""

from __future__ import annotations

import collections
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def capture(outdir: str):
    import numpy as np

    from pbccs_tpu.runtime.cache import enable_compilation_cache

    enable_compilation_cache()

    import jax

    from bench import build_tasks
    from pbccs_tpu.models.arrow.refine import RefineOptions
    from pbccs_tpu.parallel.batch import BatchPolisher

    Z = int(os.environ.get("BENCH_ZMWS", 128))
    L = int(os.environ.get("BENCH_TPL_LEN", 300))
    P = int(os.environ.get("BENCH_PASSES", 8))
    NC = int(os.environ.get("BENCH_CORRUPTIONS", 2))

    def run():
        tasks = build_tasks(np.random.default_rng(20260729), Z, L, P, NC)[0]
        p = BatchPolisher(tasks)
        p.refine(RefineOptions(max_iterations=10))
        p.consensus_qvs()

    run()  # warmup: compile everything
    with jax.profiler.trace(outdir):
        run()


def parse(outdir: str):
    from xprof.convert import raw_to_tool_data as r

    paths = glob.glob(os.path.join(outdir, "**", "*.xplane.pb"),
                      recursive=True)
    assert paths, f"no xplane.pb under {outdir}"
    paths = [max(paths, key=os.path.getmtime)]
    data, _ = r.xspace_to_tool_data(paths, "hlo_stats", {})
    table = json.loads(data if isinstance(data, str) else data.decode())
    cols = [c["id"] for c in table["cols"]]
    idx = {c: i for i, c in enumerate(cols)}
    rows = []
    for row in table["rows"]:
        v = [c.get("v") for c in row["c"]]
        rows.append({
            "category": v[idx["category"]],
            "name": v[idx["hlo_op_name"]],
            "expr": v[idx["hlo_op_expression"]] or "",
            "frame_op": v[idx["tf_op_name"]] or "",
            "occurrences": v[idx["occurrences"]] or 0,
            "self_us": v[idx["total_self_time"]] or 0.0,
        })
    return paths[0], rows


# PROFILE_r0N region buckets: keyword -> region, FIRST match wins (order
# matters: "dynamic-slice" must hit before "slice").  Shared by this
# tool's rollup and bench.py's per-row device_regions_ms attribution.
_REGION_KEYS = [
    ("kernels", ("custom-call", "custom call", "mosaic", "pallas")),
    ("dynamic_slice", ("dynamic-slice", "dynamic slice",
                       "dynamic-update-slice", "gather", "scatter")),
    ("data_formatting", ("copy", "transpose", "concatenate", "convert",
                         "reshape", "bitcast")),
    ("slice_pad", ("slice", "pad")),
    ("fusion", ("fusion", "loop", "while", "conditional")),
]


def region_rollup(rows) -> dict:
    """Collapse hlo_stats rows into the PROFILE region buckets.

    Returns {"total_ms", "kernel_fraction", "regions": {region: ms}} --
    the per-BENCH-row attribution that makes kernel-share regressions
    visible round over round (a polish whose kernel_fraction drops is
    re-growing the layout/pad overhead this round removed)."""
    per = {name: 0.0 for name, _ in _REGION_KEYS}
    per["other"] = 0.0
    for r in rows:
        hay = " ".join((r.get("category") or "",
                        r.get("name") or "",
                        r.get("frame_op") or "")).lower()
        for name, keys in _REGION_KEYS:
            if any(k in hay for k in keys):
                per[name] += r["self_us"]
                break
        else:
            per["other"] += r["self_us"]
    total = sum(per.values())
    return {
        "total_ms": round(total / 1e3, 1),
        "kernel_fraction": round(per["kernels"] / total, 4) if total else 0.0,
        "regions": {k: round(v / 1e3, 1) for k, v in per.items()},
    }


def main():
    outdir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/pbccs_trace"
    if not os.environ.get("PBCCS_TRACE_PARSE_ONLY"):
        capture(outdir)
    path, rows = parse(outdir)
    total = sum(r["self_us"] for r in rows)
    per_cat = collections.defaultdict(float)
    for r in rows:
        per_cat[r["category"]] += r["self_us"]
    print(f"# parsed {path}", file=sys.stderr)
    print(f"# total device self time: {total / 1e3:.1f} ms", file=sys.stderr)
    print("\n== category rollup (ms, % of device) ==", file=sys.stderr)
    rollup = sorted(per_cat.items(), key=lambda kv: -kv[1])
    for cat, us in rollup:
        print(f"{cat:28s} {us / 1e3:10.1f}  {100 * us / total:5.1f}%",
              file=sys.stderr)
    print("\n== top ops by self time (ms | % | occurrences) ==",
          file=sys.stderr)
    ops = sorted(rows, key=lambda r: -r["self_us"])[:40]
    for r in ops:
        label = r["frame_op"] or r["name"]
        print(f"{r['self_us'] / 1e3:9.2f} {100 * r['self_us'] / total:5.1f}% "
              f"x{r['occurrences']:<6} {r['category']:16s} {label[:90]}",
              file=sys.stderr)
    print(json.dumps({
        "total_device_ms": round(total / 1e3, 1),
        "region_rollup": region_rollup(rows),
        "categories": {k: round(v / 1e3, 1) for k, v in rollup},
        "top_ops": [{"name": (r["frame_op"] or r["name"])[:160],
                     "category": r["category"],
                     "ms": round(r["self_us"] / 1e3, 2),
                     "n": r["occurrences"]} for r in ops[:15]],
    }))


if __name__ == "__main__":
    main()
