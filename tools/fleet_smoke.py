#!/usr/bin/env python
"""Fleet smoke for the tier-1 gate: 3 `ccs serve` replicas behind `ccs
router`, with chaos at PROCESS granularity.

The serve/sched smokes prove resilience when a DEVICE dies inside one
process; this gate proves the replica tier: a whole `ccs serve` process
vanishing (kill -9) or leaving politely (SIGTERM drain) mid-stream must
cost ZERO requests -- every submit is answered exactly once, and every
consensus is byte-identical to the offline driver.

Legs:

  baseline  offline process_chunks over the workload (the byte-identity
            reference), computed in-process
  trace     the fleet observability plane: a router-driven trace capture
            fans out to every replica, requests submitted WITH wire
            trace context stream through, and the stopped capture merges
            (tools/trace_merge.py) into one Perfetto timeline in which
            every request's spans form ONE connected tree crossing the
            router and a replica process under one trace_id; a single
            router `metrics` scrape returns replica-labeled exposition
            for every replica.  Both artifacts (merged trace, federated
            exposition) are written to $ARTIFACTS_DIR (default
            /tmp/ccs-fleet-artifacts) for CI upload.
  kill9     24 requests streamed through the router; one replica with
            requests in flight is kill -9'd: every request answers
            EXACTLY once (raw-socket reply counting, not a client that
            would mask duplicates), all Success, sequences + QVs
            byte-identical to offline, ccs_router_failovers_total moved
  drain     a second round; one replica gets SIGTERM under load: the
            replica announces CCS-SERVE-DRAINING, exits 0, and again
            zero lost / zero duplicated / byte-identical

The workload reuses the chaos-cell geometry (tpl 60, 5 passes, seed
20260803) so its compiled shapes are already in the persistent cache
from the chaos/fuzz smokes.  Replica subprocesses inherit this process's
environment (same polish path as the offline baseline).

Run:  JAX_PLATFORMS=cpu python tools/fleet_smoke.py
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import time

import numpy as np

sys.path.insert(0, ".")  # runnable as tools/fleet_smoke.py from the repo root

N_ZMWS = 12
REPLICAS = 3
REPLY_TIMEOUT_S = 600.0


def check(name: str, ok: bool, detail: str = "") -> None:
    print(f"  {'PASS' if ok else 'FAIL'}  {name}"
          + (f"  ({detail})" if detail else ""), flush=True)
    if not ok:
        raise SystemExit(f"fleet smoke failed: {name} {detail}")


def make_workload():
    from pbccs_tpu.models.arrow.params import decode_bases
    from pbccs_tpu.pipeline import Chunk, Subread
    from pbccs_tpu.simulate import simulate_zmw

    rng = np.random.default_rng(20260803)
    chunks, wires = [], []
    for i in range(N_ZMWS):
        _, reads, _, snr = simulate_zmw(rng, 60, 5)
        zid = f"fleet/{i}"
        chunks.append(Chunk(
            zid, [Subread(f"{zid}/{k}", r) for k, r in enumerate(reads)],
            snr))
        wires.append({"id": zid, "snr": [float(s) for s in snr],
                      "reads": [{"seq": decode_bases(r)} for r in reads]})
    return chunks, wires


def spawn_ready(subcmd_args: list[str], marker: str
                ) -> tuple[subprocess.Popen, int, list[str]]:
    """One `ccs <subcmd>` subprocess; block until its machine-readable
    ready line (`CCS-*-READY HOST PORT`) and return (proc, port,
    pre-ready stdout lines) -- the extra lines carry secondary ready
    markers like CCS-METRICS-READY, printed before the main one."""
    proc = subprocess.Popen(
        [sys.executable, "-m", "pbccs_tpu.cli"] + subcmd_args,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
    preamble: list[str] = []
    line = proc.stdout.readline()
    while line and not line.startswith(marker):
        preamble.append(line)
        line = proc.stdout.readline()
    if not line:
        proc.kill()
        raise SystemExit(f"{marker} never seen (rc={proc.poll()})")
    return proc, int(line.split()[2]), preamble


def spawn_replica() -> tuple[subprocess.Popen, int]:
    proc, port, _pre = spawn_ready(
        ["serve", "--port", "0", "--maxBatch", "4", "--maxWaitMs", "250",
         # the router multiplexes every client over ONE replica session:
         # size the per-session cap to the admission bound so the armor
         # (built for hostile clients) never throttles the trusted link
         "--maxInflightPerSession", "256",
         "--drainTimeout", "300", "--logLevel", "ERROR"],
        "CCS-SERVE-READY")
    return proc, port


def spawn_router(ports: list[int]
                 ) -> tuple[subprocess.Popen, int, int]:
    """Router subprocess with an ephemeral HTTP /metrics endpoint;
    returns (proc, router_port, metrics_port).  CCS-METRICS-READY is
    printed before CCS-ROUTER-READY, so it rides spawn_ready's
    preamble."""
    argv = ["router", "--port", "0", "--logLevel", "ERROR",
            "--routerHealthInterval", "0.5", "--routerHealthTimeout", "3",
            "--metricsPort", "-1"]
    for p in ports:
        argv += ["--replica", f"127.0.0.1:{p}"]
    proc, port, preamble = spawn_ready(argv, "CCS-ROUTER-READY")
    metrics_port = next(
        (int(line.split()[2]) for line in preamble
         if line.startswith("CCS-METRICS-READY")), -1)
    return proc, port, metrics_port


def router_status(port: int) -> dict:
    with socket.create_connection(("127.0.0.1", port), timeout=30.0) as c:
        c.sendall(b'{"verb":"status","id":"st"}\n')
        rf = c.makefile("rb")
        while True:
            msg = json.loads(rf.readline())
            if msg.get("id") == "st":
                return msg


def router_verb(port: int, frame: dict, timeout: float = 60.0) -> dict:
    """One-shot verb round trip on a fresh router session."""
    rid = frame.get("id")
    with socket.create_connection(("127.0.0.1", port),
                                  timeout=timeout) as c:
        c.sendall(json.dumps(frame).encode() + b"\n")
        rf = c.makefile("rb")
        while True:
            msg = json.loads(rf.readline())
            if msg.get("id") == rid:
                return msg


def router_metrics_body(port: int) -> str:
    return router_verb(port, {"verb": "metrics", "id": "m"}).get("body", "")


def router_metrics(port: int) -> dict[str, float]:
    out: dict[str, float] = {}
    for line in router_metrics_body(port).splitlines():
        if line and not line.startswith("#"):
            name, _, value = line.rpartition(" ")
            try:
                out[name] = float(value)
            except ValueError:
                continue
    return out


def run_leg(name: str, router_port: int, wires, prefix: str,
            chaos) -> dict[str, dict]:
    """Submit every ZMW on one raw session, run `chaos(submitted)` once
    requests are demonstrably in flight, then count EVERY reply frame:
    exactly one per request id (a dedup failure shows up as a second
    frame, which a re-associating client would silently mask)."""
    conn = socket.create_connection(("127.0.0.1", router_port),
                                    timeout=REPLY_TIMEOUT_S)
    rf = conn.makefile("rb")
    ids = [f"{prefix}{i}" for i in range(len(wires))]
    for rid, z in zip(ids, wires):
        conn.sendall(json.dumps(
            {"verb": "submit", "id": rid, "zmw": z}).encode() + b"\n")
    chaos()
    counts = {rid: 0 for rid in ids}
    results: dict[str, dict] = {}
    try:
        while len(results) < len(ids):
            line = rf.readline()
            if not line:
                break
            msg = json.loads(line)
            rid = msg.get("id")
            if rid in counts:
                counts[rid] += 1
                results[rid] = msg
    except (socket.timeout, TimeoutError):
        pass  # lost requests surface in the zero-lost check below
    # linger to catch any late duplicate frame the router failed to dedup
    conn.settimeout(2.0)
    extras = 0
    try:
        while True:
            line = rf.readline()
            if not line:
                break
            if json.loads(line).get("id") in counts:
                extras += 1
    except (socket.timeout, TimeoutError):
        pass
    conn.close()
    check(f"{name}: zero lost requests", len(results) == len(ids),
          f"{len(results)}/{len(ids)} answered")
    check(f"{name}: zero duplicated requests",
          extras == 0 and all(c == 1 for c in counts.values()),
          f"extras={extras} counts={sorted(set(counts.values()))}")
    check(f"{name}: all Success",
          all(m.get("status") == "Success" for m in results.values()),
          str({m.get("status") or m.get("code")
               for m in results.values()}))
    return results


def artifacts_dir() -> str:
    d = os.environ.get("ARTIFACTS_DIR", "/tmp/ccs-fleet-artifacts")
    os.makedirs(d, exist_ok=True)
    return d


def run_trace_leg(router_port: int, metrics_port: int, wires) -> None:
    """The observability-plane leg: fleet-wide trace capture + merged
    timeline + federated metrics scrape (see module docstring)."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import trace_merge

    start = router_verb(router_port,
                        {"verb": "trace", "id": "ts", "action": "start"})
    check("trace: fleet capture started", start.get("state") == "started",
          str(start.get("state")))

    # submit every ZMW with wire trace context on one raw session
    conn = socket.create_connection(("127.0.0.1", router_port),
                                    timeout=REPLY_TIMEOUT_S)
    rf = conn.makefile("rb")
    trace_ids = {}
    for i, z in enumerate(wires):
        rid = f"t{i}"
        trace_ids[rid] = f"{i + 1:016x}"
        conn.sendall(json.dumps(
            {"verb": "submit", "id": rid, "zmw": z,
             "trace": {"trace_id": trace_ids[rid],
                       "span_id": f"client-{i}"}}).encode() + b"\n")
    results = {}
    while len(results) < len(wires):
        msg = json.loads(rf.readline())
        if msg.get("id") in trace_ids:
            results[msg["id"]] = msg
    conn.close()
    check("trace: all traced submits answered Success",
          all(m.get("status") == "Success" for m in results.values()),
          str({m.get("status") or m.get("code")
               for m in results.values()}))

    stop = router_verb(router_port,
                       {"verb": "trace", "id": "tp", "action": "stop"},
                       timeout=120.0)
    check("trace: fleet capture stopped", stop.get("state") == "stopped",
          str(stop.get("state")))
    check("trace: replica dumps collected",
          len(stop.get("replicas", {})) >= 2,
          f"{len(stop.get('replicas', {}))} replica dump(s)")

    merged = trace_merge.merge_docs(trace_merge.expand_bundle(stop))
    report = trace_merge.request_trees(merged)
    bad = []
    for rid, tid in trace_ids.items():
        tree = report.get(tid)
        if tree is None or tree["components"] != 1 \
                or len(tree["processes"]) < 2:
            bad.append((rid, tid, tree))
    check("trace: every request is ONE connected tree crossing "
          "router+replica", not bad, str(bad[:3]))

    # federated scrape: ONE HTTP GET on the router's --metricsPort must
    # return replica-labeled exposition for the whole fleet (the NDJSON
    # metrics verb serves the identical body)
    import urllib.request

    with urllib.request.urlopen(
            f"http://127.0.0.1:{metrics_port}/metrics",
            timeout=60.0) as resp:
        body = resp.read().decode()
    replicas = {line.split('replica="')[1].split('"')[0]
                for line in body.splitlines()
                if line.startswith("ccs_serve_admitted_total{")
                and 'replica="' in line}
    check("trace: one /metrics scrape carries >= 2 replica labels",
          len(replicas) >= 2, f"replicas={sorted(replicas)}")
    check("trace: router-local series survive federation",
          any(line.startswith("ccs_router_routed_total")
              for line in body.splitlines()))

    # CI artifacts: the merged fleet timeline + the federated snapshot
    out = artifacts_dir()
    with open(os.path.join(out, "fleet_trace.json"), "w") as f:
        json.dump(merged, f)
    with open(os.path.join(out, "fleet_metrics.prom"), "w") as f:
        f.write(body)
    print(f"  artifacts: {out}/fleet_trace.json "
          f"({len(merged['traceEvents'])} events), "
          f"{out}/fleet_metrics.prom ({len(body.splitlines())} lines)",
          flush=True)


def wait_for_victim(router_port: int, deadline_s: float = 120.0) -> str:
    """Block until some replica has requests in flight; return its name
    (the chaos target must demonstrably be mid-stream)."""
    t0 = time.monotonic()
    while time.monotonic() - t0 < deadline_s:
        st = router_status(router_port)
        busy = [r for r in st["replicas"] if r["inflight"] > 0]
        if busy:
            return max(busy, key=lambda r: r["inflight"])["replica"]
        time.sleep(0.05)
    raise SystemExit("no replica ever had requests in flight")


def main() -> int:
    from pbccs_tpu.pipeline import ConsensusSettings, process_chunks
    from pbccs_tpu.runtime.cache import enable_compilation_cache
    from pbccs_tpu.runtime.logging import Logger, LogLevel

    enable_compilation_cache()
    Logger.default(Logger(level=LogLevel.ERROR))
    chunks, wires = make_workload()

    print("== baseline (offline process_chunks) ==", flush=True)
    t0 = time.monotonic()
    offline = process_chunks(list(chunks), ConsensusSettings())
    offline_out = {r.id: (r.sequence, r.qualities)
                   for r in offline.results}
    check("baseline yields all successes",
          len(offline_out) == N_ZMWS,
          f"{len(offline_out)}/{N_ZMWS} in {time.monotonic() - t0:.0f}s")

    replicas = [spawn_replica() for _ in range(REPLICAS)]
    ports = [port for _, port in replicas]
    router_proc, router_port, metrics_port = spawn_router(ports)
    try:
        print("== leg: fleet trace + metrics federation ==", flush=True)
        run_trace_leg(router_port, metrics_port, wires)

        print("== leg: replica kill -9 mid-stream ==", flush=True)
        m0 = router_metrics(router_port)

        def kill9():
            victim = wait_for_victim(router_port)
            vport = int(victim.rsplit(":", 1)[1])
            proc = replicas[ports.index(vport)][0]
            proc.kill()
            print(f"  kill -9 replica {victim}", flush=True)

        results = run_leg("kill9", router_port, wires, "k", kill9)
        got = {m["zmw"]: (m["sequence"], m["qual"])
               for m in results.values()}
        check("kill9: byte-identical to offline", got == offline_out)
        m1 = router_metrics(router_port)

        def delta(name_prefix: str) -> float:
            return (sum(v for k, v in m1.items()
                        if k.startswith(name_prefix))
                    - sum(v for k, v in m0.items()
                          if k.startswith(name_prefix)))

        check("kill9: failovers counted",
              delta("ccs_router_failovers_total") >= 1,
              f"{delta('ccs_router_failovers_total'):.0f} failover(s)")
        st = router_status(router_port)
        check("kill9: dead replica disconnected",
              sum(1 for r in st["replicas"] if not r["connected"]) >= 1)

        print("== leg: SIGTERM drain under load ==", flush=True)

        def drain():
            victim = wait_for_victim(router_port)
            vport = int(victim.rsplit(":", 1)[1])
            proc = replicas[ports.index(vport)][0]
            proc.send_signal(signal.SIGTERM)
            print(f"  SIGTERM replica {victim}", flush=True)
            drained_proc.append(proc)

        drained_proc: list[subprocess.Popen] = []
        results = run_leg("drain", router_port, wires, "d", drain)
        got = {m["zmw"]: (m["sequence"], m["qual"])
               for m in results.values()}
        check("drain: byte-identical to offline", got == offline_out)
        if drained_proc:
            rc = drained_proc[0].wait(timeout=300)
            check("drain: replica exited 0", rc == 0, f"exit {rc}")
        check("drain: health checks ran",
              sum(v for k, v in router_metrics(router_port).items()
                  if k.startswith("ccs_router_health_checks_total")) > 0)

        print("== router drains cleanly ==", flush=True)
        router_proc.send_signal(signal.SIGTERM)
        rc = router_proc.wait(timeout=60)
        check("router exited 0 on SIGTERM", rc == 0, f"exit {rc}")
    finally:
        for proc, _ in replicas:
            if proc.poll() is None:
                proc.kill()
                proc.wait(10)
        if router_proc.poll() is None:
            router_proc.kill()
            router_proc.wait(10)

    print("fleet smoke: all checks passed", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
