#!/usr/bin/env bash
# Tier-1 verification gate: a fast syntax sweep, then the exact ROADMAP.md
# tier-1 test command.  CI (.github/workflows/tier1.yml) and humans run the
# same script, so "tier-1 green" means one thing.
set -o pipefail

cd "$(dirname "$0")/.."

echo "== compileall gate =="
python -m compileall -q pbccs_tpu tools || exit 1

echo "== static analysis (ccs analyze: conc / jax / registry / exsafe / leases / proto) =="
# clean vs the committed baseline, <60s analyzer-runtime budget, and
# every rule still fires on its positive fixture; runtime is printed
# by the smoke itself
timeout -k 10 180 python tools/analyze_smoke.py || exit 1

echo "== ruff (style gate; import order advisory) =="
if command -v ruff >/dev/null 2>&1; then
    ruff check . || exit 1
    # import-block ordering: reported, not yet enforced (ruff.toml)
    ruff check --select I001 --exit-zero --statistics . 2>/dev/null || true
else
    echo "ruff not installed; skipping (CI installs and enforces it)"
fi

echo "== kernel smoke (dense interior + edge kernels vs the f64 dense oracle) =="
# interpret mode, fixed seed, prebaked-layout path; ~30 s budget
timeout -k 10 300 env JAX_PLATFORMS=cpu python tools/kernel_smoke.py || exit 1

echo "== observability smoke (trace schema) =="
timeout -k 10 600 env JAX_PLATFORMS=cpu python tools/obs_smoke.py || exit 1

echo "== chaos smoke (fault injection / quarantine / watchdog) =="
timeout -k 10 600 env JAX_PLATFORMS=cpu python tools/chaos_smoke.py || exit 1

echo "== fuzz smoke (hostile-input hardening: BAM salvage / wire armor / drain) =="
# deterministic: any finding reproduces with --seed 0 --only <CLASS>
timeout -k 10 600 env JAX_PLATFORMS=cpu python tools/fuzz_inputs.py --smoke --seed 0 || exit 1

echo "== sched smoke (device-fleet scheduler: 8-device scaling + benched-device chaos) =="
timeout -k 10 600 env JAX_PLATFORMS=cpu python tools/sched_smoke.py || exit 1

echo "== fleet smoke (serve replicas behind ccs router: kill -9 + drain, zero lost/dup) =="
timeout -k 10 900 env JAX_PLATFORMS=cpu python tools/fleet_smoke.py || exit 1

echo "== autopilot smoke (ccs fleet supervisor: respawn, quarantine, autoscale, rolling restart) =="
timeout -k 10 900 env JAX_PLATFORMS=cpu python tools/autopilot_smoke.py || exit 1

echo "== tenant smoke (TLS fleet: auth on every edge, noisy-neighbor fairness, SLO shed) =="
timeout -k 10 900 env JAX_PLATFORMS=cpu python tools/tenant_smoke.py || exit 1

echo "== endurance smoke (scaled full-cell stream: OOM + ENOSPC + kill -9, zero loss) =="
# the scaled run itself is budgeted <= 120 s warm (the smoke prints its
# runtime); the wrapper allows cold-compile headroom
timeout -k 10 300 env JAX_PLATFORMS=cpu python tools/endurance_smoke.py || exit 1

echo "== perf smoke (ledger schema + counter determinism + perf_gate vs PERF_BASELINE) =="
# two fresh-process runs of a fixed workload: CPU-deterministic ledger
# counters must be identical, the gate must pass the clean ledger in
# counters-only mode and reject a perturbed one with a structured diff
timeout -k 10 600 env JAX_PLATFORMS=cpu python tools/perf_smoke.py || exit 1

echo "== roofline smoke (CostCard determinism + ccs roofline + efficiency floor gate) =="
# two fresh-process warmups of a 2-bucket menu (shared compile cache,
# separate card stores): cards must be byte-identical, the report must
# parse, and perf_gate must enforce the new roofline fields + floor
timeout -k 10 600 env JAX_PLATFORMS=cpu python tools/roofline_smoke.py || exit 1

echo "== tune smoke (ccs tune: output-change rejection, profile ship, loader ladder, attribution) =="
# one real search over a loaded band-width grid: the output-changing
# candidate must be rejected, the profile must ship + apply + stamp
timeout -k 10 600 env JAX_PLATFORMS=cpu python tools/tune_smoke.py || exit 1

echo "== tier-1 tests =="
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider -p no:xdist \
    -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
exit $rc
