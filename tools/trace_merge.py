#!/usr/bin/env python
"""Merge per-process Chrome-trace dumps into ONE fleet Perfetto timeline.

Each process in the serving fleet (router, every `ccs serve` replica, a
traced client) captures its own span tree (pbccs_tpu/obs/trace.py).
The router's `trace` verb (action=stop) returns them all in one bundle:

    {"type": "trace", "state": "stopped",
     "trace": {..router chrome..},
     "replicas": {"host:port": {..replica chrome..}, ...}}

This tool assembles the bundle (or any set of chrome dumps) into a
single Chrome-trace JSON that ui.perfetto.dev renders as one timeline:

  * every input process gets its own pid + process_name metadata row;
  * timelines are REBASED onto one axis using each tracer's wall-clock
    origin (`meta.origin_unix`) -- perf_counter origins are per-process
    arbitrary, the wall clock is shared (sub-ms skew on one host);
  * cross-process parent links (args.remote_parent naming another
    process's args.span_id, the wire trace-context contract) become
    Chrome flow events, so a request's client -> router -> replica
    chain draws as connected arrows;
  * `meta` totals dropped/open spans across the fleet so a truncated
    capture is visible in the artifact itself.

`request_trees()` / `trace_connected()` are the assertions
tools/fleet_smoke.py and tools/obs_smoke.py gate CI on: every request's
spans must form ONE connected tree under its trace_id.

Usage:
    python tools/trace_merge.py bundle.json -o merged.json
    python tools/trace_merge.py router.json replica1.json -o merged.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def merge_docs(docs: list[tuple[str, dict]]) -> dict[str, Any]:
    """Merge (process_name, chrome_doc) pairs into one Chrome-trace
    object (see module docstring for the semantics)."""
    origins = [d.get("meta", {}).get("origin_unix")
               for _, d in docs]
    known = [o for o in origins if isinstance(o, (int, float))]
    base = min(known) if known else 0.0

    events: list[dict] = []
    processes: dict[str, int] = {}
    dropped = open_spans = 0
    by_span_id: dict[str, dict] = {}
    for i, (name, doc) in enumerate(docs):
        pid = i + 1
        processes[name] = pid
        meta = doc.get("meta", {})
        dropped += int(meta.get("dropped_spans", 0) or 0)
        open_spans += int(meta.get("open_spans", 0) or 0)
        origin = meta.get("origin_unix")
        shift_us = ((origin - base) * 1e6
                    if isinstance(origin, (int, float)) else 0.0)
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0, "args": {"name": name}})
        for ev in doc.get("traceEvents", []):
            ev = dict(ev)
            ev["pid"] = pid
            if isinstance(ev.get("ts"), (int, float)):
                ev["ts"] = round(ev["ts"] + shift_us, 1)
            events.append(ev)
            sid = ev.get("args", {}).get("span_id")
            if isinstance(sid, str):
                by_span_id.setdefault(sid, ev)

    # cross-process parent links -> Chrome flow events (drawn as arrows)
    flow_seq = 0
    flows: list[dict] = []
    for ev in events:
        rp = ev.get("args", {}).get("remote_parent")
        if not isinstance(rp, str):
            continue
        parent = by_span_id.get(rp)
        if parent is None or parent is ev:
            continue
        flow_seq += 1
        common = {"cat": "trace-context", "name": "trace", "id": flow_seq}
        flows.append({**common, "ph": "s", "pid": parent["pid"],
                      "tid": parent.get("tid", 0),
                      "ts": parent.get("ts", 0)})
        flows.append({**common, "ph": "f", "bp": "e", "pid": ev["pid"],
                      "tid": ev.get("tid", 0), "ts": ev.get("ts", 0)})
    return {
        "traceEvents": events + flows,
        "displayTimeUnit": "ms",
        "meta": {"processes": processes, "dropped_spans": dropped,
                 "open_spans": open_spans},
    }


def expand_bundle(obj: dict, router_name: str = "router"
                  ) -> list[tuple[str, dict]]:
    """(name, chrome) pairs from a router trace-stop reply bundle, or
    from a bare chrome doc (single-process input)."""
    if "replicas" in obj or ("trace" in obj
                             and "traceEvents" not in obj):
        docs = [(router_name, obj.get("trace") or {"traceEvents": []})]
        for name, chrome in sorted((obj.get("replicas") or {}).items()):
            docs.append((f"replica {name}", chrome))
        return docs
    return [(obj.get("meta", {}).get("process", router_name), obj)]


# ------------------------------------------------------- tree assertions

def request_trees(merged: dict) -> dict[str, dict[str, Any]]:
    """Per-trace_id connectivity report over a merged doc:
    {trace_id: {"events": n, "components": k, "processes": [...]}} --
    a request whose spans crossed the fleet under one trace shows
    components == 1 and len(processes) >= 2."""
    events = [ev for ev in merged.get("traceEvents", [])
              if ev.get("ph") == "X"]
    by_span_id = {ev["args"]["span_id"]: ev for ev in events
                  if isinstance(ev.get("args", {}).get("span_id"), str)}
    by_pid_index = {(ev["pid"], ev.get("id")): ev for ev in events}

    parent: dict[int, int] = {}

    def find(x: int) -> int:
        while parent.setdefault(x, x) != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    def union(a: int, b: int) -> None:
        parent[find(a)] = find(b)

    ids = {id(ev): ev for ev in events}
    for ev in events:
        args = ev.get("args", {})
        rp = args.get("remote_parent")
        if isinstance(rp, str) and rp in by_span_id:
            union(id(ev), id(by_span_id[rp]))
        p = args.get("parent")
        if p is not None and (ev["pid"], p) in by_pid_index:
            union(id(ev), id(by_pid_index[(ev["pid"], p)]))

    out: dict[str, dict[str, Any]] = {}
    for tid in sorted({ev["args"].get("trace_id") for ev in events
                       if ev.get("args", {}).get("trace_id")}):
        mine = [ev for ev in events if ev["args"].get("trace_id") == tid]
        roots = {find(id(ev)) for ev in mine}
        out[tid] = {
            "events": len(mine),
            "components": len(roots),
            "processes": sorted({ev["pid"] for ev in mine}),
            "spans": sorted({ev["name"] for ev in mine}),
        }
    del ids
    return out


def trace_connected(merged: dict, trace_id: str) -> bool:
    """True when trace_id's spans form ONE connected tree."""
    report = request_trees(merged).get(trace_id)
    return report is not None and report["components"] == 1


# ------------------------------------------------------------------- CLI

def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="trace_merge",
        description="Merge per-process CCS trace dumps (a router trace "
                    "bundle or chrome JSON files) into one Perfetto "
                    "timeline.")
    p.add_argument("inputs", nargs="+",
                   help="Router trace-stop bundle JSON and/or chrome "
                        "trace JSON files.")
    p.add_argument("-o", "--output", required=True,
                   help="Merged Chrome-trace JSON output path.")
    p.add_argument("--report", action="store_true",
                   help="Print the per-trace connectivity report.")
    args = p.parse_args(argv)

    docs: list[tuple[str, dict]] = []
    for path in args.inputs:
        with open(path) as f:
            obj = json.load(f)
        base = os.path.splitext(os.path.basename(path))[0]
        docs.extend(expand_bundle(obj, router_name=base))
    merged = merge_docs(docs)

    from pbccs_tpu.resilience.resources import atomic_output

    with atomic_output(args.output, "trace") as f:
        json.dump(merged, f)
    report = request_trees(merged)
    if args.report:
        print(json.dumps(report, indent=2))
    connected = sum(1 for r in report.values() if r["components"] == 1)
    print(f"trace_merge: {len(docs)} process(es), "
          f"{len(merged['traceEvents'])} event(s), "
          f"{len(report)} trace(s) ({connected} connected) "
          f"-> {args.output}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
