#!/usr/bin/env python
"""Merge per-process Chrome-trace dumps into ONE fleet Perfetto timeline.

Each process in the serving fleet (router, every `ccs serve` replica, a
traced client) captures its own span tree (pbccs_tpu/obs/trace.py).
The router's `trace` verb (action=stop) returns them all in one bundle:

    {"type": "trace", "state": "stopped",
     "trace": {..router chrome..},
     "replicas": {"host:port": {..replica chrome..}, ...}}

This tool assembles the bundle (or any set of chrome dumps) into a
single Chrome-trace JSON that ui.perfetto.dev renders as one timeline:

  * every input process gets its own pid + process_name metadata row;
  * timelines are REBASED onto one axis using each tracer's wall-clock
    origin (`meta.origin_unix`) -- perf_counter origins are per-process
    arbitrary, the wall clock is shared (sub-ms skew on one host);
  * cross-process parent links (args.remote_parent naming another
    process's args.span_id, the wire trace-context contract) become
    Chrome flow events, so a request's client -> router -> replica
    chain draws as connected arrows;
  * `meta` totals dropped/open spans across the fleet so a truncated
    capture is visible in the artifact itself.

`request_trees()` / `trace_connected()` are the assertions
tools/fleet_smoke.py and tools/obs_smoke.py gate CI on: every request's
spans must form ONE connected tree under its trace_id.

Usage:
    python tools/trace_merge.py bundle.json -o merged.json
    python tools/trace_merge.py router.json replica1.json -o merged.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _span_args(ev: Any) -> dict:
    """An event's args as a dict, {} for anything malformed -- the
    merge must degrade on alien events, never KeyError mid-merge."""
    args = ev.get("args") if isinstance(ev, dict) else None
    return args if isinstance(args, dict) else {}


def merge_docs(docs: list[tuple[str, dict]]) -> dict[str, Any]:
    """Merge (process_name, chrome_doc) pairs into one Chrome-trace
    object (see module docstring for the semantics).

    Degrades, never crashes: a non-dict chrome doc (a replica whose
    trace-stop reply was malformed) is SKIPPED and named in
    ``meta.skipped_processes``; a doc whose ``meta.origin_unix`` is
    missing or non-numeric stays on its own (unshifted) timebase and is
    named in ``meta.unrebased_processes``; a replica with zero spans
    merges as an empty process row.  An empty bundle yields an empty
    (but valid) merged doc."""
    usable: list[tuple[str, dict]] = []
    skipped: list[str] = []
    unrebased: list[str] = []
    for name, doc in docs:
        if isinstance(doc, dict):
            usable.append((name, doc))
        else:
            skipped.append(str(name))

    def doc_meta(doc: dict) -> dict:
        meta = doc.get("meta")
        return meta if isinstance(meta, dict) else {}

    origins = [doc_meta(d).get("origin_unix") for _, d in usable]
    known = [o for o in origins if isinstance(o, (int, float))
             and not isinstance(o, bool)]
    base = min(known) if known else 0.0

    events: list[dict] = []
    processes: dict[str, int] = {}
    dropped = open_spans = 0
    by_span_id: dict[str, dict] = {}
    for i, (name, doc) in enumerate(usable):
        pid = i + 1
        processes[name] = pid
        meta = doc_meta(doc)
        try:
            dropped += int(meta.get("dropped_spans", 0) or 0)
            open_spans += int(meta.get("open_spans", 0) or 0)
        except (TypeError, ValueError):
            pass  # alien meta counts; the span data still merges
        origin = meta.get("origin_unix")
        if isinstance(origin, (int, float)) and not isinstance(origin,
                                                               bool):
            shift_us = (origin - base) * 1e6
        else:
            shift_us = 0.0
            unrebased.append(str(name))
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0, "args": {"name": name}})
        raw = doc.get("traceEvents")
        for ev in (raw if isinstance(raw, list) else []):
            if not isinstance(ev, dict):
                continue
            ev = dict(ev)
            ev["pid"] = pid
            if isinstance(ev.get("ts"), (int, float)):
                ev["ts"] = round(ev["ts"] + shift_us, 1)
            events.append(ev)
            sid = _span_args(ev).get("span_id")
            if isinstance(sid, str):
                by_span_id.setdefault(sid, ev)

    # cross-process parent links -> Chrome flow events (drawn as arrows)
    flow_seq = 0
    flows: list[dict] = []
    for ev in events:
        rp = _span_args(ev).get("remote_parent")
        if not isinstance(rp, str):
            continue
        parent = by_span_id.get(rp)
        if parent is None or parent is ev:
            continue
        flow_seq += 1
        common = {"cat": "trace-context", "name": "trace", "id": flow_seq}
        flows.append({**common, "ph": "s", "pid": parent["pid"],
                      "tid": parent.get("tid", 0),
                      "ts": parent.get("ts", 0)})
        flows.append({**common, "ph": "f", "bp": "e", "pid": ev["pid"],
                      "tid": ev.get("tid", 0), "ts": ev.get("ts", 0)})
    meta: dict[str, Any] = {"processes": processes,
                            "dropped_spans": dropped,
                            "open_spans": open_spans}
    if skipped:
        meta["skipped_processes"] = sorted(skipped)
    if unrebased:
        meta["unrebased_processes"] = sorted(unrebased)
    return {
        "traceEvents": events + flows,
        "displayTimeUnit": "ms",
        "meta": meta,
    }


def expand_bundle(obj: dict, router_name: str = "router"
                  ) -> list[tuple[str, dict]]:
    """(name, chrome) pairs from a router trace-stop reply bundle, or
    from a bare chrome doc (single-process input)."""
    if "replicas" in obj or ("trace" in obj
                             and "traceEvents" not in obj):
        trace = obj.get("trace")
        docs = [(router_name,
                 trace if isinstance(trace, dict) else {"traceEvents": []})]
        replicas = obj.get("replicas")
        if isinstance(replicas, dict):
            for name, chrome in sorted(replicas.items()):
                # a malformed per-replica chrome rides through as-is:
                # merge_docs skips it with a meta.skipped_processes note
                docs.append((f"replica {name}", chrome))
        return docs
    meta = obj.get("meta")
    process = meta.get("process") if isinstance(meta, dict) else None
    return [(process or router_name, obj)]


# ------------------------------------------------------- tree assertions

def request_trees(merged: dict) -> dict[str, dict[str, Any]]:
    """Per-trace_id connectivity report over a merged doc:
    {trace_id: {"events": n, "components": k, "processes": [...]}} --
    a request whose spans crossed the fleet under one trace shows
    components == 1 and len(processes) >= 2."""
    def hashable(v) -> bool:
        try:
            hash(v)
        except TypeError:
            return False
        return True

    events = [ev for ev in merged.get("traceEvents", [])
              if isinstance(ev, dict) and ev.get("ph") == "X"
              and "pid" in ev]
    by_span_id = {_span_args(ev)["span_id"]: ev for ev in events
                  if isinstance(_span_args(ev).get("span_id"), str)}
    by_pid_index = {(ev["pid"], ev.get("id")): ev for ev in events
                    if hashable(ev.get("id"))}

    parent: dict[int, int] = {}

    def find(x: int) -> int:
        while parent.setdefault(x, x) != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    def union(a: int, b: int) -> None:
        parent[find(a)] = find(b)

    ids = {id(ev): ev for ev in events}
    for ev in events:
        args = _span_args(ev)
        rp = args.get("remote_parent")
        if isinstance(rp, str) and rp in by_span_id:
            union(id(ev), id(by_span_id[rp]))
        p = args.get("parent")
        if p is not None and hashable(p) \
                and (ev["pid"], p) in by_pid_index:
            union(id(ev), id(by_pid_index[(ev["pid"], p)]))

    out: dict[str, dict[str, Any]] = {}
    # only STRING trace ids participate: an alien-typed id (an int a
    # malformed replica minted) must be skipped like every other alien
    # shape, not crash the sort with a mixed-type comparison
    tids = {_span_args(ev).get("trace_id") for ev in events}
    for tid in sorted(t for t in tids if isinstance(t, str) and t):
        mine = [ev for ev in events
                if _span_args(ev).get("trace_id") == tid]
        roots = {find(id(ev)) for ev in mine}
        out[tid] = {
            "events": len(mine),
            "components": len(roots),
            "processes": sorted({ev["pid"] for ev in mine}),
            "spans": sorted({str(ev.get("name", "?")) for ev in mine}),
        }
    del ids
    return out


def trace_connected(merged: dict, trace_id: str) -> bool:
    """True when trace_id's spans form ONE connected tree."""
    report = request_trees(merged).get(trace_id)
    return report is not None and report["components"] == 1


# ------------------------------------------------------------------- CLI

def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="trace_merge",
        description="Merge per-process CCS trace dumps (a router trace "
                    "bundle or chrome JSON files) into one Perfetto "
                    "timeline.")
    p.add_argument("inputs", nargs="+",
                   help="Router trace-stop bundle JSON and/or chrome "
                        "trace JSON files.")
    p.add_argument("-o", "--output", required=True,
                   help="Merged Chrome-trace JSON output path.")
    p.add_argument("--report", action="store_true",
                   help="Print the per-trace connectivity report.")
    args = p.parse_args(argv)

    docs: list[tuple[str, dict]] = []
    for path in args.inputs:
        with open(path) as f:
            obj = json.load(f)
        base = os.path.splitext(os.path.basename(path))[0]
        docs.extend(expand_bundle(obj, router_name=base))
    merged = merge_docs(docs)

    from pbccs_tpu.resilience.resources import atomic_output

    with atomic_output(args.output, "trace") as f:
        json.dump(merged, f)
    report = request_trees(merged)
    if args.report:
        print(json.dumps(report, indent=2))
    connected = sum(1 for r in report.values() if r["components"] == 1)
    print(f"trace_merge: {len(docs)} process(es), "
          f"{len(merged['traceEvents'])} event(s), "
          f"{len(report)} trace(s) ({connected} connected) "
          f"-> {args.output}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
