#!/usr/bin/env python
"""Tier-1 gate for `ccs analyze` (pbccs_tpu/analysis).

Three assertions, mirroring the acceptance contract:

  1. the repository analyzes CLEAN against the committed baseline
     (exit 0), i.e. no unsuppressed finding and no stale suppression;
  2. the full run stays under the 60 s analyzer-runtime budget (pure
     AST, but the interprocedural passes build a whole-program call
     graph -- a blowup here means a pass grew an accidental O(n^2) and
     the suite would stop being tier-1-fast);
  3. every AST rule still FIRES on its positive fixture -- a refactor
     that silently lobotomizes a pass fails CI even though the repo
     "looks clean".

Run it exactly as CI does:   python tools/analyze_smoke.py
"""

from __future__ import annotations

import importlib.util
import pathlib
import sys
import time

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

from pbccs_tpu.analysis import run_passes  # noqa: E402
from pbccs_tpu.analysis.cli import run_analyze  # noqa: E402

FIXTURES = REPO / "tests" / "fixtures" / "analysis"
BUDGET_S = 60.0


def _load_cases() -> dict:
    spec = importlib.util.spec_from_file_location(
        "cases", FIXTURES / "cases.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.AST_CASES


def main() -> int:
    t0 = time.perf_counter()
    rc = run_analyze(["--root", str(REPO)])
    dt = time.perf_counter() - t0
    print(f"analyze_smoke: repo run rc={rc} in {dt:.2f}s "
          f"(budget {BUDGET_S:.0f}s)")
    if rc != 0:
        print("analyze_smoke: FAIL -- `ccs analyze` must exit 0 on the "
              "repo against the committed baseline", file=sys.stderr)
        return 1
    if dt >= BUDGET_S:
        print(f"analyze_smoke: FAIL -- analyzer took {dt:.1f}s "
              f"(>= {BUDGET_S:.0f}s budget)", file=sys.stderr)
        return 1

    bad = 0
    for rule, (pos, _neg) in sorted(_load_cases().items()):
        findings = run_passes(FIXTURES, paths=[FIXTURES / pos])
        fired = any(f.rule == rule for f in findings)
        # the CLI contract: a positive fixture makes `ccs analyze` exit
        # non-zero (path-scoped, no baseline)
        cli_rc = run_analyze(["--root", str(FIXTURES), "--no-baseline",
                              str(FIXTURES / pos)])
        print(f"analyze_smoke: {rule} on {pos}: "
              f"{'fires' if fired else 'SILENT'} (cli rc={cli_rc})")
        if not fired or cli_rc == 0:
            bad += 1
    if bad:
        print(f"analyze_smoke: FAIL -- {bad} rule(s) no longer fire on "
              "their positive fixtures", file=sys.stderr)
        return 1
    print("analyze_smoke: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
