#!/usr/bin/env python
"""Component-level wall-clock attribution for the device-resident polish.

Times each stage of the polish loop separately (block_until_ready between
stages, median of repeats) so the round-3 kernel work attacks the measured
bottleneck instead of a guessed one:

  * setup          BatchPolisher(...) construction (windows + first fills)
  * fill           one fill_alpha_beta_batch_zr over the (Z, R) grid --
                   the per-round rebuild cost inside the loop
  * loop[n]        run_refine_loop with max_iterations=n; the n=1 -> full
                   slope separates per-round cost from fixed overhead
  * qv             consensus_qvs sweep

Usage: python tools/profile_polish.py [--repeats 5]
Env: BENCH_ZMWS/BENCH_TPL_LEN/BENCH_PASSES/BENCH_CORRUPTIONS as bench.py.
Writes a JSON summary to stdout (one line) and a human table to stderr.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def med_time(fn, repeats=3):
    ts = []
    for _ in range(repeats):
        t0 = time.monotonic()
        fn()
        ts.append(time.monotonic() - t0)
    ts.sort()
    return ts[len(ts) // 2], ts


def main():
    import numpy as np

    from pbccs_tpu.runtime.cache import enable_compilation_cache

    enable_compilation_cache()

    import jax
    import jax.numpy as jnp

    from bench import build_tasks
    from pbccs_tpu.models.arrow.refine import RefineOptions
    from pbccs_tpu.models.arrow.scorer import (fill_alpha_beta_batch_zr,
                                               fills_use_pallas)
    from pbccs_tpu.parallel.batch import BatchPolisher

    repeats = int(sys.argv[sys.argv.index("--repeats") + 1]) \
        if "--repeats" in sys.argv else 3
    Z = int(os.environ.get("BENCH_ZMWS", 128))
    L = int(os.environ.get("BENCH_TPL_LEN", 300))
    P = int(os.environ.get("BENCH_PASSES", 8))
    NC = int(os.environ.get("BENCH_CORRUPTIONS", 2))
    rng = np.random.default_rng(20260729)
    out = {"platform": jax.devices()[0].platform, "Z": Z, "L": L, "P": P}

    def fresh_tasks():
        return build_tasks(np.random.default_rng(20260729), Z, L, P, NC)[0]

    # ---- setup ----------------------------------------------------------
    BatchPolisher(fresh_tasks())  # warmup/compile
    t, _ = med_time(lambda: BatchPolisher(fresh_tasks()), repeats)
    out["setup_s"] = round(t, 4)

    p = BatchPolisher(fresh_tasks())

    # ---- raw fill (the loop's per-round rebuild core) -------------------
    use_pal = fills_use_pallas()
    # ccs-analyze: ignore[JAX004] -- jitted ONCE here, reused across repeats
    filled = jax.jit(
        lambda: fill_alpha_beta_batch_zr(
            p._reads_dev, p._rlens_dev, p.win_tpl, p.win_trans, p.wlens,
            p._W, use_pal))

    def run_fill():
        jax.block_until_ready(filled())

    run_fill()
    t, _ = med_time(run_fill, repeats)
    out["fill_zr_s"] = round(t, 4)

    # ---- device loop at several round budgets ---------------------------
    loop_s = {}
    for iters in (1, 2, 4, 10):
        def run_loop(iters=iters):
            pp = BatchPolisher(fresh_tasks())
            res = pp.refine(RefineOptions(max_iterations=iters))
            assert res is not None
        run_loop()  # compile at this static budget
        t, ts = med_time(run_loop, repeats)
        loop_s[iters] = round(t, 4)
    out["refine_s_by_iters"] = loop_s
    # per-round slope from the 2->10 segment (round counts actually run
    # shrink as ZMWs converge; slope is still the right order)
    out["per_round_slope_s"] = round((loop_s[10] - loop_s[2]) / 8, 4)

    # ---- QV sweep -------------------------------------------------------
    pp = BatchPolisher(fresh_tasks())
    pp.refine(RefineOptions(max_iterations=10))
    pp.consensus_qvs()
    t, _ = med_time(lambda: pp.consensus_qvs(), repeats)
    out["qv_sweep_s"] = round(t, 4)

    # ---- one full polish for reference ----------------------------------
    def full():
        pp = BatchPolisher(fresh_tasks())
        pp.refine(RefineOptions(max_iterations=10))
        pp.consensus_qvs()
    t, _ = med_time(full, repeats)
    out["full_polish_s"] = round(t, 4)
    out["zmws_per_sec"] = round(Z / t, 2)

    hdr = f"{'stage':24s} {'seconds':>10s}"
    print(hdr, file=sys.stderr)
    for k, v in out.items():
        print(f"{k:24s} {v!s:>10s}", file=sys.stderr)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
